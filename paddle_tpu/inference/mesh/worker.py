"""Mesh worker process: one full ContinuousBatchingEngine behind the
frame transport.

Launched by ProcessReplicaPool (transport="socket") as
`python -m paddle_tpu.inference.mesh.worker --connect HOST:PORT
--name replicaN --spec /path/spec.json` — the two_proc_worker idiom: a
plain subprocess, CPU-pinned jax, rendezvous over native TCP. The spec
is a JSON-safe engine recipe (callables cannot cross a process): model
config kwargs, engine kwargs, role, and the parent's TCPStore endpoint.

The worker owns its OWN mesh lease: it registers an ElasticManager over
the parent's native TCPStore and runs the threaded heartbeat
(`manager.start()`), so membership is real cross-process lease-keeping
— kill -9 this process and the lease goes stale exactly like a lost
node in an etcd registry. The serve loop is serial: recv frame ->
serve_request -> reply; request pipelining (async KV imports overlapping
the parent's pump) comes from the parent writing ahead on the socket.

Exit paths: a "shutdown" frame (clean retire — reply first, then
deregister so the tombstone is ordered after the last reply), or the
parent/socket dying (the lease lapses by ttl; the parent writes the
tombstone on kill so membership converges immediately).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))))

import jax

# the worker must be a pure-CPU process regardless of host plugins (the
# two_proc_worker discipline: sitecustomize may force-select TPU)
jax.config.update("jax_platforms", "cpu")


def build_engine(spec):
    """Engine from a JSON-safe recipe. Weights are deterministic by
    seed — every worker built from the same spec holds the same model,
    the invariant disaggregated handoff relies on."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    paddle.seed(int(spec.get("seed", 0)))
    cfg = LlamaConfig(**spec.get("config", {}))
    model = LlamaForCausalLM(cfg)
    kw = dict(spec.get("engine", {}))
    buckets = kw.get("prefill_buckets")
    if buckets is not None:
        kw["prefill_buckets"] = tuple(buckets)
    return ContinuousBatchingEngine(model, **kw)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--connect", required=True, help="parent HOST:PORT")
    ap.add_argument("--name", required=True)
    ap.add_argument("--spec", required=True, help="spec JSON path")
    args = ap.parse_args(argv)

    with open(args.spec) as f:
        spec = json.load(f)

    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    from paddle_tpu.distributed.store import TCPStore
    from paddle_tpu.inference.mesh.transport import (
        recv_frame, send_frame, serve_request)

    engine = build_engine(spec)
    exports = []
    if spec.get("role") == "prefill":
        engine.prefill_sink = exports.append

    # the worker's own lease over the parent's store, threaded beats —
    # real cross-process membership (beat failures counted, never fatal)
    manager = None
    st = spec.get("store") or {}
    if st.get("port"):
        try:
            store = TCPStore(host=st.get("host", "127.0.0.1"),
                             port=int(st["port"]), is_master=False,
                             timeout=10)
            manager = ElasticManager(
                store, node_id=spec.get("node_id", args.name),
                heartbeat_interval=float(
                    st.get("heartbeat_interval", 5.0)))
            manager.register()
            manager.start()
        except Exception:  # noqa: BLE001 — membership is the parent's
            manager = None  # problem to notice (stale lease), not ours

    # connect budget mirrors the parent's accept budget: spec override,
    # else the registered FLAGS_mesh_worker_accept_timeout_s default
    from paddle_tpu.framework.flags import flag_value
    connect_timeout = spec.get("accept_timeout_s")
    if connect_timeout is None:
        connect_timeout = flag_value("mesh_worker_accept_timeout_s")
    host, port = args.connect.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)),
                                    timeout=float(connect_timeout))
    # the serve loop legitimately blocks forever waiting for its parent;
    # the connect budget must not double as an idle-read timeout
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    try:
        while True:
            kind, meta, payload = recv_frame(sock)
            rk, rm, rp = serve_request(engine, kind, meta, payload,
                                       exports=exports)
            send_frame(sock, rk, rm, rp)
            if kind == "shutdown":
                break
    finally:
        if manager is not None:
            manager.deregister()
        sock.close()


if __name__ == "__main__":
    main()
