"""Continuous-batching serving engine over the paged KV cache.

reference capability: the serving loop the reference builds around
block_multihead_attention (paddle/phi/kernels/fusion/gpu/
block_multi_head_attention_kernel.cu + incubate/nn/functional/
block_multihead_attention.py): block tables, iteration-level scheduling,
in-flight admission of new sequences while others decode.

TPU-native design: TWO compiled programs serve every request mix.
  - prefill: full-prompt forward at bucketed lengths (pad to the next
    bucket so a handful of executables cover all prompts), returning the
    first sampled token and the prompt's per-layer K/V for the host to
    scatter into the block pool.
  - decode: one token for ALL active lanes at once — fixed max_batch
    lanes (inactive lanes masked), dense [B, max_blocks] block tables,
    paged-attention gather over the pool (ops/paged_attention.py). Static
    shapes mean XLA compiles each program once; admission/retirement is
    pure host bookkeeping between steps.
Memory is allocated in block_size granules from one (L, num_blocks, ...)
pool — no per-sequence max-length reservation, exactly the property the
reference's block attention exists for.

Prefill attention is routed per bucket shape by the same baked backend
ledger as training (ops/pallas/attention_router, consulted inside
generation._llama_layer_prefill at trace time); `attention_route` keeps
the largest bucket's decision for audit.
"""

from __future__ import annotations

import time
import warnings
from collections import deque

import numpy as np

import jax
import jax.numpy as jnp

from ..generation import _llama_layer_prefill, _rms, _rope
from ..observability import span as _span
from ..observability.catalog import metric as _metric
from ..ops.paged_attention import paged_attention_decode, write_to_cache
from ..resilience.faults import FaultInjected, fault_point

__all__ = ["ContinuousBatchingEngine", "Request", "BackpressureError"]


class BackpressureError(RuntimeError):
    """add_request refused: the admission queue is at max_queue. The
    caller (gateway/load balancer) should retry later or route away —
    that is the backpressure signal, instead of unbounded queueing."""


class Request:
    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "generated", "done", "do_sample", "temperature", "top_k",
                 "top_p", "rng", "t_arrival", "deadline_s", "t_deadline",
                 "finish_reason", "shed_count")

    def __init__(self, rid, prompt, max_new_tokens, eos_token_id,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 seed=None, deadline_s=None):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.generated: list[int] = []
        self.done = False
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        # None -> OS entropy: concurrent sampled requests must differ by
        # default; a fixed seed is the explicit-reproducibility opt-in
        self.rng = np.random.RandomState(seed)
        self.t_arrival = time.perf_counter()   # TTFT anchor
        # degraded completions are distinguishable: finish_reason is one
        # of eos / length / timeout / shed / rejected (None while live)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.t_deadline = (None if deadline_s is None
                           else self.t_arrival + float(deadline_s))
        self.finish_reason = None
        self.shed_count = 0

    def choose(self, logits: np.ndarray) -> int:
        """Per-request next-token choice on the host (B is small; the
        reference's top_p_sampling semantics: temperature -> top-k ->
        nucleus filter -> categorical)."""
        if not self.do_sample:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / max(self.temperature, 1e-6)
        if self.top_k > 0:
            kth = np.sort(z)[-min(self.top_k, z.size)]
            z = np.where(z < kth, -np.inf, z)
        if self.top_p < 1.0:
            p = np.exp(z - np.max(z))
            p /= p.sum()
            order = np.argsort(-p)
            cum = np.cumsum(p[order])
            keep_sorted = (cum - p[order]) < self.top_p
            keep_sorted[0] = True  # top_p=0 must still keep the argmax
            keep = np.zeros_like(keep_sorted)
            keep[order] = keep_sorted
            z = np.where(keep, z, -np.inf)
        p = np.exp(z - np.max(z))
        p /= p.sum()
        return int(self.rng.choice(p.size, p=p))


class _LayeredBlockPool:
    """Block allocator over a (L, num_blocks, block_size, KVH, D) pool.
    One block-id table per sequence, shared by all layers."""

    def __init__(self, num_layers, num_blocks, block_size, kv_heads,
                 head_dim, dtype):
        self.block_size = block_size
        self.num_blocks = num_blocks
        shape = (num_layers, num_blocks, block_size, kv_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # the LAST block is the scratch target for inactive decode lanes:
        # every lane writes its token's K/V unconditionally inside the
        # compiled step (no data-dependent skips), so masked lanes must
        # scribble somewhere no live sequence owns
        self.scratch_block = num_blocks - 1
        self._free = list(range(num_blocks - 2, -1, -1))
        self.tables: dict[int, list[int]] = {}

    def blocks_needed(self, n_tokens):
        return (n_tokens + self.block_size - 1) // self.block_size

    def can_fit(self, n_tokens):
        return len(self._free) >= self.blocks_needed(n_tokens)

    def ensure(self, rid, n_tokens):
        table = self.tables.setdefault(rid, [])
        need = self.blocks_needed(n_tokens)
        while len(table) < need:
            if not self._free:
                raise MemoryError("paged KV pool exhausted")
            table.append(self._free.pop())
        return table

    def release(self, rid):
        for b in self.tables.pop(rid, []):
            self._free.append(b)

    def write_prompt(self, rid, ks, vs, length):
        """ks/vs: (L, S_pad, KVH, D); writes the first `length` positions."""
        table = self.ensure(rid, length)
        bs = self.block_size
        span = len(table) * bs
        pad = span - ks.shape[1]
        if pad > 0:
            ks = jnp.pad(ks, ((0, 0), (0, pad), (0, 0), (0, 0)))
            vs = jnp.pad(vs, ((0, 0), (0, pad), (0, 0), (0, 0)))
        elif pad < 0:
            ks = ks[:, :span]
            vs = vs[:, :span]
        ids = jnp.asarray(table, jnp.int32)
        L = ks.shape[0]
        kb = ks.reshape(L, len(table), bs, *ks.shape[2:])
        vb = vs.reshape(L, len(table), bs, *vs.shape[2:])
        self.k = self.k.at[:, ids].set(kb)
        self.v = self.v.at[:, ids].set(vb)


class ContinuousBatchingEngine:
    """Iteration-level scheduler: admit -> decode-step -> retire.

    model: LlamaForCausalLM. Per-request decoding knobs (greedy default;
    do_sample with temperature/top_k/top_p + per-request seed) are applied
    host-side on the returned logits row — mixed greedy/sampled lanes
    share one compiled decode step.
    """

    def __init__(self, model, num_blocks=256, block_size=16, max_batch=8,
                 max_blocks_per_seq=64,
                 prefill_buckets=(64, 128, 256, 512, 1024),
                 max_queue=None, max_sheds=2):
        config = model.config
        self.cfg = dict(eps=config.rms_norm_eps, theta=config.rope_theta,
                        heads=config.num_attention_heads,
                        kv_heads=config.num_key_value_heads,
                        head_dim=(config.hidden_size //
                                  config.num_attention_heads))
        state = {k: v._data for k, v in model.state_dict().items()}
        from ..parallel.functional import split_stacked_layer_params
        self.stacked, other = split_stacked_layer_params(state)
        self.embed_w = other["llama.embed_tokens.weight"]
        self.norm_w = other["llama.norm.weight"]
        self.head_w = other.get("lm_head.weight")  # None == tied
        # tied models: transpose ONCE — passing embed_w.T per call would
        # re-materialize a (hidden, vocab) device array every token
        self._out_w = self.head_w if self.head_w is not None \
            else jnp.asarray(self.embed_w).T
        L = config.num_hidden_layers
        self.pool = _LayeredBlockPool(L, num_blocks, block_size,
                                      self.cfg["kv_heads"],
                                      self.cfg["head_dim"],
                                      self.embed_w.dtype)
        self.max_batch = int(max_batch)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.buckets = tuple(sorted(prefill_buckets))
        # prefill attention backend comes from the same baked per-shape
        # router/ledger as the train path (generation._llama_layer_prefill
        # consults it per bucket at trace time); keep the largest bucket's
        # decision here for audit/metrics
        try:
            from ..ops.pallas.attention_router import route
            self.attention_route = route(
                self.cfg["heads"], self.buckets[-1], self.buckets[-1],
                self.cfg["head_dim"], self.embed_w.dtype, True)
        except (ImportError, OSError, ValueError, KeyError) as e:
            # audit-only probe: a missing/broken ledger must not stop the
            # engine, but it is logged + counted, never silently nulled
            self.attention_route = None
            warnings.warn(
                f"serving attention-route probe failed ({e!r}); "
                "per-bucket routing still happens at prefill trace time",
                RuntimeWarning, stacklevel=2)
            _metric("serving_route_probe_failures_total").inc()
        self.max_queue = None if max_queue is None else int(max_queue)
        self.max_sheds = int(max_sheds)
        self.lanes: list[Request | None] = [None] * self.max_batch
        self.lane_len = np.zeros(self.max_batch, np.int64)  # tokens in cache
        self.lane_tok = np.zeros(self.max_batch, np.int64)  # next to write
        self.queue: deque[Request] = deque()
        self.finished: dict[int, Request] = {}
        self._next_rid = 0
        self._prefill_jit = {}
        self._decode_jit = None
        # PIR compile pipeline reports per program (prefill.b<bucket> /
        # decode): cache hit/miss + pass stats — the engine warm-start
        # evidence bench.py and tests read
        self.compile_reports: dict[str, object] = {}
        # observability handles bound ONCE (catalog names; no-op when the
        # layer is disabled — each call is a single flag check)
        self._m_ttft = _metric("serving_ttft_seconds")
        self._m_tpot = _metric("serving_tpot_seconds")
        self._m_prefill = _metric("serving_prefill_seconds")
        self._m_queue = _metric("serving_queue_depth")
        self._m_occ = _metric("serving_batch_occupancy")
        self._m_free = _metric("serving_kv_free_blocks")
        self._m_admitted = _metric("serving_admitted_total")
        self._m_retired = _metric("serving_retired_total")
        self._m_tokens = _metric("serving_tokens_total")
        _metric("serving_preempted_total")  # declared: 0 by design

    # --- public API -------------------------------------------------------
    def add_request(self, prompt, max_new_tokens=32, eos_token_id=None,
                    do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                    seed=0, deadline_s=None):
        """Queue a request. `deadline_s` is a per-request wall-clock
        budget from arrival: once exceeded the request finishes with
        whatever it has and finish_reason='timeout'. Raises
        BackpressureError when the admission queue is at max_queue."""
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            _metric("serving_backpressure_total").inc()
            raise BackpressureError(
                f"admission queue full ({len(self.queue)}/{self.max_queue}); "
                "retry later")
        rid = self._next_rid
        self._next_rid += 1
        self.queue.append(Request(rid, prompt, max_new_tokens, eos_token_id,
                                  do_sample, temperature, top_k, top_p,
                                  seed, deadline_s))
        return rid

    def has_work(self):
        return bool(self.queue) or any(r is not None for r in self.lanes)

    def run(self, max_steps=10_000):
        """Drive to completion; returns {rid: [generated tokens]}."""
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return {rid: r.generated for rid, r in self.finished.items()}

    # --- scheduling -------------------------------------------------------
    def step(self):
        with _span("serving.step"):
            self._expire_deadlines()
            self._m_queue.set(len(self.queue))
            self._admit()
            self._decode_step()
            self._m_occ.set(sum(r is not None for r in self.lanes)
                            / self.max_batch)
            self._m_free.set(len(self.pool._free))

    # --- graceful degradation --------------------------------------------
    def _finish(self, req, reason):
        req.done = True
        req.finish_reason = reason
        self.finished[req.rid] = req
        _metric("serving_finished_total", reason=reason).inc()

    def _retire_lane(self, lane, reason):
        req = self.lanes[lane]
        self.pool.release(req.rid)
        self.lanes[lane] = None
        self.lane_len[lane] = 0
        self._m_retired.inc()
        self._finish(req, reason)

    def _expire_deadlines(self):
        """Per-request deadlines: an expired queued request finishes
        empty; an expired decoding lane finishes with the tokens it has
        (a degraded-but-distinguishable completion) and its pool blocks
        are released."""
        now = time.perf_counter()
        if any(r.t_deadline is not None and now >= r.t_deadline
               for r in self.queue):
            kept = deque()
            for req in self.queue:
                if req.t_deadline is not None and now >= req.t_deadline:
                    _metric("serving_timeouts_total", where="queue").inc()
                    self._finish(req, "timeout")
                else:
                    kept.append(req)
            self.queue = kept
        for lane, req in enumerate(self.lanes):
            if (req is not None and req.t_deadline is not None
                    and now >= req.t_deadline):
                _metric("serving_timeouts_total", where="decode").inc()
                self._retire_lane(lane, "timeout")

    def _shed(self, active):
        """Decode-step OOM: preempt the lane with the least work done
        (fewest generated tokens), release its blocks, and requeue the
        request at the FRONT of the queue for a fresh prefill. A request
        shed more than max_sheds times finishes degraded
        (finish_reason='shed') instead of thrashing the pool forever."""
        victim = max(active,
                     key=lambda i: (-len(self.lanes[i].generated), i))
        req = self.lanes[victim]
        self.pool.release(req.rid)
        self.lanes[victim] = None
        self.lane_len[victim] = 0
        req.shed_count += 1
        _metric("serving_shed_total").inc()
        if req.shed_count > self.max_sheds:
            self._m_retired.inc()
            self._finish(req, "shed")
            return
        # restart from the prompt next admission: the KV blocks are gone,
        # and greedy decode reproduces the same prefix deterministically
        req.generated = []
        self.queue.appendleft(req)

    def _admit(self):
        while self.queue:
            free_lanes = [i for i, r in enumerate(self.lanes) if r is None]
            if not free_lanes:
                return
            req = self.queue[0]
            total = req.prompt.size + req.max_new_tokens
            if (total > self.max_blocks_per_seq * self.pool.block_size
                    or req.prompt.size > self.buckets[-1]):
                # cannot ever serve: reject with an empty result instead
                # of crashing the engine mid-step
                self.queue.popleft()
                req.generated = []
                self._finish(req, "rejected")
                _metric("serving_rejected_total", reason="oversized").inc()
                continue
            if req.max_new_tokens <= 0:
                self.queue.popleft()
                self._finish(req, "length")
                continue
            # admit only if the WHOLE sequence fits: no mid-flight
            # eviction (the reference engine preempts; we keep the
            # no-surprise contract and leave the request queued)
            if not self.pool.can_fit(total):
                _metric("serving_deferred_total", reason="pool_full").inc()
                return
            self.queue.popleft()
            lane = free_lanes[0]
            try:
                fault_point("serve.admit", rid=req.rid)
                with _span("serving.prefill", rid=req.rid,
                           prompt=int(req.prompt.size)):
                    t0 = time.perf_counter()
                    first_tok = self._prefill(req)
                    self._m_prefill.observe(time.perf_counter() - t0)
                # reserve the FULL footprint now — lazy per-step allocation
                # could exhaust the pool mid-decode across admitted
                # sequences, which the admission check above promised
                # cannot happen
                self.pool.ensure(req.rid, total)
            except MemoryError:
                # pool exhausted despite the can_fit gate (e.g. blocks
                # held by an out-of-band allocation): surface as a counted
                # deferral, give back any partial reservation, and leave
                # the request AT THE FRONT of the queue — never let the
                # scheduler step die mid-flight
                self.pool.release(req.rid)
                self.queue.appendleft(req)
                _metric("serving_deferred_total",
                        reason="pool_exhausted").inc()
                return
            except (TimeoutError, ConnectionError, OSError,
                    FaultInjected):
                # transient admission failure (store/IO blip or injected
                # fault): same counted-deferral contract — requeued at
                # the front, retried next step, scheduler stays alive
                self.pool.release(req.rid)
                self.queue.appendleft(req)
                _metric("serving_deferred_total",
                        reason="admit_fault").inc()
                return
            self.lanes[lane] = req
            self.lane_len[lane] = req.prompt.size
            self.lane_tok[lane] = first_tok
            self._m_admitted.inc()
            self._m_ttft.observe(time.perf_counter() - req.t_arrival)
            self._emit(lane, first_tok)

    def _emit(self, lane, token):
        req = self.lanes[lane]
        req.generated.append(int(token))
        self._m_tokens.inc()
        if (req.eos_token_id is not None
                and int(token) == req.eos_token_id):
            self._retire_lane(lane, "eos")
        elif len(req.generated) >= req.max_new_tokens:
            self._retire_lane(lane, "length")

    # --- compiled programs ------------------------------------------------
    def _bucket(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds the largest prefill "
                         f"bucket {self.buckets[-1]}")

    def _prefill(self, req):
        s = req.prompt.size
        bucket = self._bucket(s)
        fn = self._prefill_jit.get(bucket)
        if fn is None:
            # engine warm-start: prefill programs compile through the PIR
            # pipeline — pattern-rewritten pre-XLA and, with
            # FLAGS_compile_cache_dir set, warm-loaded from the persistent
            # compile cache instead of paying the cold XLA compile
            from ..pir import pir_jit
            fn = pir_jit(self._make_prefill(),
                         name=f"serving.prefill.b{bucket}")
            self._prefill_jit[bucket] = fn
            self.compile_reports[f"prefill.b{bucket}"] = None
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :s] = req.prompt
        logits, ks, vs = fn(self.stacked, self.embed_w, self.norm_w,
                            self._out_w, jnp.asarray(ids), jnp.int32(s))
        if self.compile_reports.get(f"prefill.b{bucket}") is None:
            self.compile_reports[f"prefill.b{bucket}"] = \
                getattr(fn, "report", None)
        self.pool.write_prompt(req.rid, ks[:, 0], vs[:, 0], s)
        return req.choose(np.asarray(logits).reshape(-1))

    def _make_prefill(self):
        cfg = self.cfg

        def run(stacked, embed_w, norm_w, head_w, ids, length):
            b, s = ids.shape
            pos = jnp.broadcast_to(jnp.arange(s)[None], (b, s))

            def layer(h, lp):
                h, (k, v) = _llama_layer_prefill(lp, h, pos, cfg)
                return h, (k, v)

            h = jnp.take(embed_w, ids, axis=0)
            h, (ks, vs) = jax.lax.scan(layer, h, stacked)
            h_last = h[:, length - 1]          # dynamic index: traced length
            logits = (_rms(h_last, norm_w, cfg["eps"]) @ head_w).astype(
                jnp.float32)
            return logits, ks, vs

        return run

    def _decode_step(self):
        active = [i for i, r in enumerate(self.lanes) if r is not None]
        if not active:
            return
        t0 = time.perf_counter()
        try:
            with _span("serving.decode_step", active=len(active)):
                self._decode_step_inner(active)
        except MemoryError:
            # device OOM (or the serve.decode_oom fault site): shed one
            # lane and requeue it rather than killing every in-flight
            # request; the remaining lanes decode on the next step
            self._shed(active)
            return
        except Exception as e:  # noqa: BLE001 — XLA OOM is backend-typed
            if "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e):
                self._shed(active)
                return
            raise
        # one compiled step advances every active lane one token, so the
        # step wall time IS the per-token latency (TPOT)
        self._m_tpot.observe(time.perf_counter() - t0)

    def _decode_step_inner(self, active):
        fault_point("serve.decode_oom", active=len(active))
        B = self.max_batch
        MB = self.max_blocks_per_seq
        # inactive lanes write into the pool's scratch block (their rows
        # would otherwise point at block 0, corrupting a live sequence);
        # active lanes' blocks were fully reserved at admission
        tables = np.full((B, MB), self.pool.scratch_block, np.int32)
        for i in active:
            t = self.pool.tables[self.lanes[i].rid]
            tables[i, :len(t)] = t
        lens = np.zeros(B, np.int32)
        for i in active:
            lens[i] = self.lane_len[i]
        toks = np.zeros(B, np.int32)
        for i in active:
            toks[i] = self.lane_tok[i]
        mask = np.zeros(B, bool)
        mask[active] = True

        if self._decode_jit is None:
            # decode keeps donation (the KV pools must not double-buffer),
            # so the pipeline runs but the artifact store is bypassed
            # (pir reports cache="bypass:donate")
            from ..pir import pir_jit
            self._decode_jit = pir_jit(self._make_decode(),
                                       name="serving.decode",
                                       donate_argnums=(4, 5))
        logits, self.pool.k, self.pool.v = self._decode_jit(
            self.stacked, self.embed_w, self.norm_w, self._out_w,
            self.pool.k, self.pool.v, jnp.asarray(toks), jnp.asarray(tables),
            jnp.asarray(lens), jnp.asarray(mask))
        if self.compile_reports.get("decode") is None:
            self.compile_reports["decode"] = getattr(self._decode_jit,
                                                     "report", None)
        if any(self.lanes[i].do_sample for i in active):
            logits_np = np.asarray(logits)
            chosen = {i: self.lanes[i].choose(logits_np[i]) for i in active}
        else:
            # all-greedy (the serving default): argmax on device, transfer
            # B ints instead of the (B, vocab) fp32 logits every token
            nxt_all = np.asarray(jnp.argmax(logits, axis=-1))
            chosen = {i: int(nxt_all[i]) for i in active}
        for i in active:
            nxt = chosen[i]
            self.lane_len[i] += 1
            self.lane_tok[i] = nxt
            self._emit(i, nxt)

    def _make_decode(self):
        cfg = self.cfg

        def run(stacked, embed_w, norm_w, head_w, kpool, vpool, toks,
                tables, lens, mask):
            eps, theta = cfg["eps"], cfg["theta"]
            nh, nkv, hd = cfg["heads"], cfg["kv_heads"], cfg["head_dim"]
            B = toks.shape[0]
            h = jnp.take(embed_w, toks[:, None], axis=0)  # (B, 1, H)
            pos = lens[:, None]                            # write position

            def layer(carry, xs):
                hh = carry
                lp, kc, vc = xs
                x = _rms(hh, lp["input_layernorm.weight"], eps)
                q = (x @ lp["self_attn.q_proj.weight"]).reshape(B, 1, nh, hd)
                k = (x @ lp["self_attn.k_proj.weight"]).reshape(B, 1, nkv, hd)
                v = (x @ lp["self_attn.v_proj.weight"]).reshape(B, 1, nkv, hd)
                q = _rope(q, pos, theta)[:, 0]
                k = _rope(k, pos, theta)[:, 0]
                v = v[:, 0]
                kc, vc = write_to_cache(kc, vc, k, v, tables, lens)
                attn = paged_attention_decode(
                    q, kc, vc, tables, lens + 1,
                    scale=1.0 / (hd ** 0.5))
                hh = hh + (attn.reshape(B, 1, nh * hd)
                           @ lp["self_attn.o_proj.weight"])
                x = _rms(hh, lp["post_attention_layernorm.weight"], eps)
                gate = x @ lp["mlp.gate_proj.weight"]
                up = x @ lp["mlp.up_proj.weight"]
                hh = hh + (jax.nn.silu(gate) * up) @ lp["mlp.down_proj.weight"]
                return hh, (kc, vc)

            h, (kpool, vpool) = jax.lax.scan(layer, h, (stacked, kpool, vpool))
            logits = (_rms(h[:, 0], norm_w, eps) @ head_w).astype(jnp.float32)
            logits = jnp.where(mask[:, None], logits, -1e30)
            return logits, kpool, vpool

        return run
