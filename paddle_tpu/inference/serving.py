"""Continuous-batching serving engine over the paged KV cache.

reference capability: the serving loop the reference builds around
block_multihead_attention (paddle/phi/kernels/fusion/gpu/
block_multi_head_attention_kernel.cu + incubate/nn/functional/
block_multihead_attention.py): block tables, iteration-level scheduling,
in-flight admission of new sequences while others decode.

TPU-native design (round 9: fused multi-token decode): TWO compiled
program families serve every request mix.

  - chunked prefill: a prompt is split into fixed-width chunks; each
    chunk forward writes its K/V into the paged pool (multi-token
    scatter) and attends over all previously cached positions, so a
    1024-token prompt interleaves with decode steps instead of
    head-of-line-blocking every active lane. One compiled program per
    chunk width.
  - fused K-step decode: ONE `lax.scan` advances all lanes
    `decode_steps` tokens per dispatch — on-device greedy argmax (and
    on-device per-lane categorical sampling for sampled lanes),
    on-device paged-cache writes, on-device EOS/length masking —
    returning a [B, K] token tile instead of one token per host
    round-trip.

Lane state (block tables, seq lens, next-token ids, alive mask, sampling
knobs) is DEVICE-RESIDENT: uploaded only when lane membership changes
(admission / retire / shed), never rebuilt from numpy in the steady
state (`serving_lane_state_uploads_total` counts refreshes). Dispatch is
double-buffered: tile N+1 is enqueued before tile N's tokens are read
back, so host bookkeeping overlaps device compute
(`serving_dispatch_ahead_depth`, `serving_hostsync_seconds`).

Memory is allocated in block_size granules from one (L, num_blocks, ...)
pool — no per-sequence max-length reservation, exactly the property the
reference's block attention exists for.
"""

from __future__ import annotations

import os
import time
import warnings
from collections import deque

import numpy as np

import jax
import jax.numpy as jnp

from ..generation import _llama_layer_prefill_chunk, _rms, _rope
from .adapters import AdapterLoadError
from ..observability import span as _span
from ..observability.catalog import metric as _metric
from ..observability.metrics import get_registry as _get_registry
from ..observability.recorder import get_recorder as _get_recorder
from ..observability.tracing import LANE_TID_BASE
from ..observability.tracing import get_tracer as _get_tracer
from ..observability.tracing import new_trace_id as _new_trace_id
from ..ops.paged_attention import (KVBlockFormat, kv_rollback_tokens,
                                   kv_write_token, kv_write_tokens,
                                   paged_attention_decode_inner,
                                   paged_attention_verify, write_to_cache)
from ..profiler.phases import get_phase_accountant as _get_phases
from ..resilience.faults import FaultInjected, fault_point
from .prefix_cache import PrefixCacheIndex
from .scheduler import PRIORITY_CLASSES, SLOScheduler

__all__ = ["ContinuousBatchingEngine", "Request", "BackpressureError",
           "KVPoolExhaustedError"]

# exception classes that mean "transient trouble, retry next step" when
# they surface from an admission / prefill-chunk / host-sync seam
_TRANSIENT_ERRORS = (TimeoutError, ConnectionError, OSError, FaultInjected)


class BackpressureError(RuntimeError):
    """add_request refused: the admission queue is at max_queue. The
    caller (gateway/load balancer) should retry later or route away —
    that is the backpressure signal, instead of unbounded queueing."""


class KVPoolExhaustedError(MemoryError):
    """The paged KV pool has no free block for a reservation. A typed
    MemoryError subclass so the existing shed/defer-on-MemoryError paths
    keep working while callers (and the metrics catalog:
    serving_pool_exhausted_total) can tell pool pressure apart from a
    real device OOM."""


class Request:
    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "generated", "done", "do_sample", "temperature", "top_k",
                 "top_p", "rng", "sample_seed", "t_arrival", "deadline_s",
                 "t_deadline", "finish_reason", "shed_count", "trace_id",
                 "tenant", "priority", "t_first", "adapter", "adapter_id")

    def __init__(self, rid, prompt, max_new_tokens, eos_token_id,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 seed=None, deadline_s=None, tenant="-",
                 priority="interactive", adapter=None):
        self.rid = rid
        # named LoRA adapter (round 22) — None/"" = the base model.
        # adapter_id is the device pool slot, bound at admission by the
        # engine's AdapterStore (0 = base, an exact-zeros delta).
        self.adapter = str(adapter) if adapter else None
        self.adapter_id = 0
        # per-tenant telemetry label; "-" = unattributed (the default
        # keeps every pre-tenant caller's label sets unchanged)
        self.tenant = str(tenant) if tenant else "-"
        # scheduling class (closed registry: scheduler.PRIORITY_CLASSES);
        # validated at add_request, defaulted here so direct Request
        # construction in tests keeps working
        self.priority = priority
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.generated: list[int] = []
        self.done = False
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        # None -> OS entropy: concurrent sampled requests must differ by
        # default; a fixed seed is the explicit-reproducibility opt-in.
        # The same seed feeds the host RandomState (first token, sampled
        # at prefill) and the device per-lane PRNG lane key (decode
        # tokens, folded with the absolute position so the stream is
        # identical no matter how decode steps are tiled).
        self.rng = np.random.RandomState(seed)
        self.sample_seed = (np.uint32(seed & 0xFFFFFFFF)
                            if seed is not None else
                            np.uint32(int.from_bytes(os.urandom(4),
                                                     "little")))
        self.t_arrival = time.perf_counter()   # TTFT anchor
        self.t_first = None                    # first-token wall time
        # degraded completions are distinguishable: finish_reason is one
        # of eos / length / timeout / shed / rejected (None while live)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.t_deadline = (None if deadline_s is None
                           else self.t_arrival + float(deadline_s))
        self.finish_reason = None
        self.shed_count = 0
        # joins this request's spans, histogram exemplars, and flight-
        # recorder events; generated unconditionally (one f-string) so a
        # request is correlatable even if tracing turns on mid-flight
        self.trace_id = _new_trace_id("req-")

    def choose(self, logits: np.ndarray) -> int:
        """Per-request next-token choice on the host — used for the
        FIRST token only (sampled once per request at prefill; decode
        tokens are chosen on device inside the fused scan). Semantics:
        temperature -> top-k -> nucleus filter -> categorical."""
        if not self.do_sample:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / max(self.temperature, 1e-6)
        if self.top_k > 0:
            kth = np.sort(z)[-min(self.top_k, z.size)]
            z = np.where(z < kth, -np.inf, z)
        if self.top_p < 1.0:
            p = np.exp(z - np.max(z))
            p /= p.sum()
            order = np.argsort(-p)
            cum = np.cumsum(p[order])
            keep_sorted = (cum - p[order]) < self.top_p
            keep_sorted[0] = True  # top_p=0 must still keep the argmax
            keep = np.zeros_like(keep_sorted)
            keep[order] = keep_sorted
            z = np.where(keep, z, -np.inf)
        p = np.exp(z - np.max(z))
        p /= p.sum()
        return int(self.rng.choice(p.size, p=p))


class _LayeredBlockPool:
    """Block allocator over a (L, num_blocks, block_size, KVH, D) pool.
    One block-id table per sequence, shared by all layers.

    Round 18: blocks are REFCOUNTED. A block's references are (a) each
    request whose table holds it and (b) an optional prefix-cache pin
    (`pin`/`unpin`) that keeps a prompt-prefix block resident after its
    request retires. `release` decrements instead of freeing, so two
    requests sharing a system-prompt prefix return the block exactly
    once — when the last holder lets go. Shared blocks are only ever
    READ (prompt positions are immutable after prefill; decode and
    speculative writes land at positions >= the prompt length, i.e. in
    later, private blocks); the one write that can land inside a shared
    block — the >=1-token prefill tail of a block-aligned full-prefix
    match — goes through `fork_cow` first."""

    def __init__(self, num_layers, num_blocks, block_size, kv_heads,
                 head_dim, dtype, fmt=None):
        self.block_size = block_size
        self.num_blocks = num_blocks
        # storage format of the blocks (round 11): quantized formats hold
        # int8/fp8 payloads plus a parallel per-(token, head) scale pool;
        # passthrough formats ARE the pre-round-11 pool, byte-identical
        self.fmt = fmt if fmt is not None else KVBlockFormat(
            "native", native_dtype=dtype)
        store = self.fmt.store_dtype if self.fmt.quantized else dtype
        shape = (num_layers, num_blocks, block_size, kv_heads, head_dim)
        self.k = jnp.zeros(shape, store)
        self.v = jnp.zeros(shape, store)
        if self.fmt.quantized:
            sshape = (num_layers, num_blocks, block_size, kv_heads)
            self.k_scale = jnp.zeros(sshape, self.fmt.scale_dtype)
            self.v_scale = jnp.zeros(sshape, self.fmt.scale_dtype)
        else:
            self.k_scale = self.v_scale = None
        # the LAST block is the scratch target for inactive decode lanes:
        # every lane writes its token's K/V unconditionally inside the
        # compiled step (no data-dependent skips), so masked lanes must
        # scribble somewhere no live sequence owns
        self.scratch_block = num_blocks - 1
        self._free = list(range(num_blocks - 2, -1, -1))
        self.tables: dict[int, list[int]] = {}
        # block id -> reference count; absent == free (on self._free)
        self._ref: dict[int, int] = {}

    def blocks_needed(self, n_tokens):
        return (n_tokens + self.block_size - 1) // self.block_size

    def can_fit(self, n_tokens, have=0):
        return len(self._free) >= self.blocks_needed(n_tokens) - have

    def _deref(self, b):
        n = self._ref.get(b, 1) - 1
        if n <= 0:
            self._ref.pop(b, None)
            self._free.append(b)
        else:
            self._ref[b] = n

    def ensure(self, rid, n_tokens):
        table = self.tables.setdefault(rid, [])
        need = self.blocks_needed(n_tokens)
        while len(table) < need:
            if not self._free:
                _metric("serving_pool_exhausted_total").inc()
                raise KVPoolExhaustedError("paged KV pool exhausted")
            b = self._free.pop()
            self._ref[b] = 1
            table.append(b)
        return table

    def release(self, rid):
        for b in self.tables.pop(rid, []):
            self._deref(b)

    # --- cross-request prefix sharing (round 18) --------------------------
    def adopt(self, rid, blocks):
        """Start rid's table with already-resident shared blocks (a
        prefix-cache hit): each gains a reference; the tail of the table
        is filled by the usual ensure()."""
        table = self.tables.setdefault(rid, [])
        if table:
            raise ValueError(f"adopt on rid {rid} with a non-empty table")
        for b in blocks:
            self._ref[b] = self._ref.get(b, 0) + 1
            table.append(int(b))
        return table

    def pin(self, b):
        """Prefix-cache reference: keeps the block resident after its
        request retires."""
        self._ref[b] = self._ref.get(b, 0) + 1

    def unpin(self, b):
        """Drop a prefix-cache reference (index eviction / clear). The
        block frees only when no request still holds it."""
        self._deref(b)

    def shared_count(self, rid):
        """How many of rid's blocks are shared (refcount > 1) — the
        handoff manifest's shared-block marker."""
        return sum(1 for b in self.tables.get(rid, ())
                   if self._ref.get(b, 1) > 1)

    def fork_cow(self, rid, idx):
        """Copy-on-write: give rid a PRIVATE copy of table[idx] before a
        write lands in it. Device-copies the stored payload (and scales)
        byte-for-byte into a fresh block, swaps the table entry, and
        drops the old reference. No-op when the block is already
        private. Raises KVPoolExhaustedError when no free block exists
        (callers treat it like any reservation failure)."""
        old = self.tables[rid][idx]
        if self._ref.get(old, 1) <= 1:
            return old
        if not self._free:
            _metric("serving_pool_exhausted_total").inc()
            raise KVPoolExhaustedError(
                "paged KV pool exhausted (copy-on-write fork)")
        new = self._free.pop()
        self._ref[new] = 1
        self.k = self.k.at[:, new].set(self.k[:, old])
        self.v = self.v.at[:, new].set(self.v[:, old])
        if self.fmt.quantized:
            self.k_scale = self.k_scale.at[:, new].set(self.k_scale[:, old])
            self.v_scale = self.v_scale.at[:, new].set(self.v_scale[:, old])
        self.tables[rid][idx] = new
        self._deref(old)
        return new


class _PrefillTask:
    """A prompt being prefilled chunk-by-chunk: `pieces` is the
    precomputed (start, width) plan; the task owns its lane (the lane is
    occupied but NOT decode-active until the final chunk completes)."""

    __slots__ = ("req", "lane", "pieces", "idx")

    def __init__(self, req, lane, pieces):
        self.req = req
        self.lane = lane
        self.pieces = pieces
        self.idx = 0


class _Inflight:
    """One dispatched-but-unread decode tile: the [B, K] token tile
    future plus the lane snapshot (request refs + lane epochs) needed to
    credit tokens only to lanes whose occupancy did not change while the
    tile was in flight."""

    __slots__ = ("tile", "t_dispatch", "reqs", "epochs", "k", "covers_all",
                 "tile_id", "spec", "key")

    def __init__(self, tile, t_dispatch, reqs, epochs, k, covers_all,
                 tile_id=0, spec=False, key=None):
        self.tile = tile
        self.t_dispatch = t_dispatch
        self.reqs = reqs
        self.epochs = epochs
        self.k = k
        self.covers_all = covers_all
        self.tile_id = tile_id
        # speculative tiles are (tokens [B, K, D+1], counts [B, K]) pairs
        # instead of a [B, K] array; per-tile, not per-engine, so tiles
        # dispatched before a speculation-off degradation drain correctly
        self.spec = spec
        self.key = key      # compile_reports key of the dispatched program


class ContinuousBatchingEngine:
    """Iteration-level scheduler: admit -> fused decode tile -> retire.

    model: LlamaForCausalLM. Per-request decoding knobs (greedy default;
    do_sample with temperature/top_k/top_p + per-request seed) ride the
    device-resident lane state — mixed greedy/sampled lanes share one
    compiled fused decode step.

    Tuning knobs (PERF.md "Fused multi-token serving decode"):
      decode_steps: tokens every lane advances per dispatch (the K of
        the fused scan). 1 reproduces the old step-per-token engine.
      prefill_chunk: max prompt tokens per prefill chunk (default: the
        largest prefill bucket — prompts beyond it now chunk instead of
        being rejected).
      prefill_chunks_per_step: chunks advanced per engine step while
        decode lanes are active (back-to-back when none are).
      compat_step_loop: reproduce the pre-fused host-bound loop —
        decode_steps forced to 1, lane state rebuilt from numpy and
        re-uploaded EVERY step, every tile drained synchronously (no
        dispatch-ahead). The bench A/B baseline, and a fully-synchronous
        debug mode (nothing in flight between steps).

    Round-11 knobs (PERF.md "Speculative decode + quantized KV"):
      speculative_decode: each fused scan step proposes draft_depth
        tokens from the drafter, verifies them in ONE batched forward
        and commits the accepted run plus a correction token — up to
        K*(draft_depth+1) tokens per dispatch, greedy streams
        byte-identical to the non-speculative path.
      draft_depth: draft tokens per scan step (clamped to block_size-1
        so one step's writes never alias within a block).
      draft_ngram: context length of the built-in n-gram/prompt-lookup
        drafter.
      drafter: pluggable draft hook `fn(hist, lens, toks, depth) ->
        [B, depth] int32`, traced inside the compiled program (a cheap
        draft model goes here); None = the built-in n-gram drafter.
      kv_cache_dtype: paged-pool block format — "bf16"/"native" (store
        the model dtype; the PR-5-identical pool), "int8", "fp8_e4m3",
        "fp8_e5m2" (quantized payloads + per-(token, head) scales,
        dequant fused into the attention reads).
      kv_pool_bytes: size the pool by HBM budget instead of num_blocks —
        int8 fits ~2x the lanes of bf16 in the same bytes (test-pinned
        >=1.9x).

    Round-14 knob (RESILIENCE.md "Overload runbook"):
      scheduler: the closed-loop SLO scheduler (scheduler.SLOScheduler)
        — priority classes with decode-lane preemption, per-tenant DRR
        fairness + lane quotas, and the reversible brownout ladder.
        None (default) = plain FIFO admission, exactly the
        pre-scheduler engine; True = an SLOScheduler with defaults; or
        pass a configured instance.

    Round-18 knobs (PERF.md "Prefix cache"):
      prefix_cache: cross-request prompt-prefix sharing (off by
        default — the pre-round-18 engine). Admission
        resolves the prompt's leading block-aligned chunks against a
        chained-hash index of already-resident paged-KV blocks; prefill
        runs only on the unmatched tail, shared blocks are refcounted,
        and the one write that could land in a shared block (the tail
        of a block-aligned full match) forks a private copy first
        (COW). Greedy/sampled streams are byte-identical with the
        cache on or off (test-pinned). Index failures degrade to a
        cache miss (serve.prefix_match fault site) — never a wrong
        stream.
      prefix_cache_blocks: optional cap on indexed blocks (LRU-evicted
        past it). None = bounded only by pool pressure: admission
        evicts LRU index entries before deferring on a full pool.
    """

    def __init__(self, model, num_blocks=256, block_size=16, max_batch=8,
                 max_blocks_per_seq=64,
                 prefill_buckets=(64, 128, 256, 512, 1024),
                 max_queue=None, max_sheds=2, decode_steps=4,
                 prefill_chunk=None, prefill_chunks_per_step=1,
                 compat_step_loop=False, speculative_decode=False,
                 draft_depth=2, draft_ngram=3, drafter=None,
                 kv_cache_dtype="bf16", kv_pool_bytes=None,
                 scheduler=None, prefix_cache=False,
                 prefix_cache_blocks=None, adapters=None):
        config = model.config
        self.cfg = dict(eps=config.rms_norm_eps, theta=config.rope_theta,
                        heads=config.num_attention_heads,
                        kv_heads=config.num_key_value_heads,
                        head_dim=(config.hidden_size //
                                  config.num_attention_heads))
        state = {k: v._data for k, v in model.state_dict().items()}
        from ..parallel.functional import split_stacked_layer_params
        self.stacked, other = split_stacked_layer_params(state)
        self.embed_w = other["llama.embed_tokens.weight"]
        self.norm_w = other["llama.norm.weight"]
        self.head_w = other.get("lm_head.weight")  # None == tied
        # tied models: transpose ONCE — passing embed_w.T per call would
        # re-materialize a (hidden, vocab) device array every token
        self._out_w = self.head_w if self.head_w is not None \
            else jnp.asarray(self.embed_w).T
        L = config.num_hidden_layers
        fmt = KVBlockFormat(kv_cache_dtype, native_dtype=self.embed_w.dtype)
        if kv_pool_bytes is not None:
            # size the pool by byte budget: blocks = budget / bytes-per-
            # block (k AND v, all layers, payload + scales) — the knob
            # that makes int8's ~2x lane capacity a measurable contract
            per_block = (L * block_size * 2 *
                         fmt.bytes_per_token(self.cfg["kv_heads"],
                                             self.cfg["head_dim"]))
            num_blocks = max(2, int(kv_pool_bytes) // per_block)
        self.pool = _LayeredBlockPool(L, num_blocks, block_size,
                                      self.cfg["kv_heads"],
                                      self.cfg["head_dim"],
                                      self.embed_w.dtype, fmt=fmt)
        self.max_batch = int(max_batch)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.buckets = tuple(sorted(prefill_buckets))
        self.compat_step_loop = bool(compat_step_loop)
        self.decode_steps = (1 if self.compat_step_loop
                             else max(1, int(decode_steps)))
        # speculative decode rides the fused scan; the compat loop is by
        # definition the pre-fused engine, so it never speculates
        self.spec = bool(speculative_decode) and not self.compat_step_loop
        # depth cap: one step writes draft_depth+1 contiguous slots per
        # lane; keeping that <= block_size guarantees the write and its
        # rollback never alias within a block
        self.draft_depth = max(1, min(int(draft_depth), block_size - 1))
        self.draft_ngram = max(2, int(draft_ngram))
        self._drafter = drafter
        self.chunk = int(prefill_chunk or self.buckets[-1])
        self.prefill_chunks_per_step = max(1, int(prefill_chunks_per_step))
        # chunk widths a prefill piece may compile at: every bucket that
        # fits inside a chunk, plus the chunk width itself (the tail
        # piece pads to the smallest width that fits)
        self._chunk_widths = sorted(
            {b for b in self.buckets if b <= self.chunk} | {self.chunk})
        # prefill attention backend comes from the same baked per-shape
        # router/ledger as the train path; keep the largest width's
        # decision here for audit/metrics
        try:
            from ..ops.pallas.attention_router import route
            self.attention_route = route(
                self.cfg["heads"], self._chunk_widths[-1],
                self._chunk_widths[-1], self.cfg["head_dim"],
                self.embed_w.dtype, True)
        except (ImportError, OSError, ValueError, KeyError) as e:
            # audit-only probe: a missing/broken ledger must not stop the
            # engine, but it is logged + counted, never silently nulled
            self.attention_route = None
            warnings.warn(
                f"serving attention-route probe failed ({e!r}); "
                "per-bucket routing still happens at prefill trace time",
                RuntimeWarning, stacklevel=2)
            _metric("serving_route_probe_failures_total").inc()
        self.max_queue = None if max_queue is None else int(max_queue)
        self.max_sheds = int(max_sheds)
        self.lanes: list[Request | None] = [None] * self.max_batch
        self.lane_len = np.zeros(self.max_batch, np.int64)  # tokens in cache
        self.lane_tok = np.zeros(self.max_batch, np.int64)  # next to write
        # occupancy epoch per lane: bumped on every retire/shed/assign so
        # an in-flight tile can never credit tokens across an occupancy
        # change (the lane snapshot carries the epochs it was dispatched
        # under)
        self._lane_epoch = np.zeros(self.max_batch, np.int64)
        self.queue: deque[Request] = deque()
        self.finished: dict[int, Request] = {}
        self._next_rid = 0
        self._prefill_jit = {}                 # chunk width -> pir_jit
        self._prefill_tasks: dict[int, _PrefillTask] = {}
        self._decode_jit = {}                  # variant -> pir_jit
        # device-resident lane state (toks/lens/alive/rem/eos/tables +
        # sampling knobs); rebuilt from the host mirrors ONLY when
        # membership changes (self._dirty)
        self._dev = None
        self._dirty = True
        self._inflight: deque[_Inflight] = deque()
        # PIR compile pipeline reports per program (prefill.b<width> /
        # decode[.sampled]): cache hit/miss + pass stats — the engine
        # warm-start evidence bench.py and tests read
        self.compile_reports: dict[str, object] = {}
        # observability handles bound ONCE (catalog names; no-op when the
        # layer is disabled — each call is a single flag check)
        self._m_ttft = _metric("serving_ttft_seconds")
        self._m_tpot = _metric("serving_tpot_seconds")
        self._m_prefill = _metric("serving_prefill_seconds")
        self._m_queue = _metric("serving_queue_depth")
        self._m_occ = _metric("serving_batch_occupancy")
        self._m_free = _metric("serving_kv_free_blocks")
        self._m_admitted = _metric("serving_admitted_total")
        self._m_retired = _metric("serving_retired_total")
        self._m_tokens = _metric("serving_tokens_total")
        self._m_uploads = _metric("serving_lane_state_uploads_total")
        self._m_dispatches = _metric("serving_decode_dispatches_total")
        self._m_ahead = _metric("serving_dispatch_ahead_depth")
        self._m_hostsync = _metric("serving_hostsync_seconds")
        self._m_hostsync_retries = _metric("serving_hostsync_retries_total")
        self._m_chunks = _metric("serving_prefill_chunks_total")
        self._m_draft = _metric("serving_draft_tokens_total")
        self._m_accept = _metric("serving_accepted_tokens_total")
        self._m_accept_rate = _metric("serving_spec_acceptance_rate")
        self._m_tok_disp = _metric("serving_tokens_per_dispatch")
        _metric("serving_preempted_total")  # incremented by _try_preempt
        # request-scoped telemetry handles, bound once; every hot-path
        # use is guarded by a single `.enabled` attribute check so the
        # disabled engine pays no allocation (kwargs pack at call sites)
        self._tracer = _get_tracer()
        self._reg = _get_registry()
        self._rec = _get_recorder()
        self._tile_seq = 0              # decode tile ids for span links
        # per-phase wall-time accountant (profiler/phases.py): every
        # mutation is disabled-noop, so the engine marks unconditionally
        self._phases = _get_phases()
        # bounded-cardinality tenant label set: past the cap new tenants
        # collapse to "overflow" so a label-per-user bug cannot blow up
        # the registry (MAX_LABEL_SETS)
        self._tenants: set[str] = set()
        self._max_tenants = 32
        # cost-model calibration: raw roofline seconds are TPU-ledger
        # priced; the first measured dispatch fixes the platform +
        # overhead scale so later predicted-vs-measured ratios are
        # relative-accuracy signals on any backend
        self._cost_scale = None
        self._m_cost_err = _metric("pir_cost_model_error")
        # round 14: the closed-loop SLO scheduler. Base knob values are
        # captured here so the brownout ladder's degradations are
        # REVERSIBLE (level 0 restores them); _spec_allowed separates
        # the reversible brownout switch from the permanent
        # draft_verify-fault degradation.
        self._base_decode_steps = self.decode_steps
        self._base_draft_depth = self.draft_depth
        self._base_chunk = self.chunk
        self._mnt_cap = None
        self._spec_allowed = self.spec
        # rid -> (request, cached length, next token): decode lanes
        # parked by preemption. Pool blocks stay allocated — resuming is
        # a lane-state re-upload, not a re-prefill.
        self._preempted: dict[int, tuple[Request, int, int]] = {}
        # round 16 (mesh disaggregation): a prefill-pool worker sets
        # this to a callable; the final prefill chunk then serializes
        # the request's paged-KV state through export_kv and hands the
        # record to the sink INSTEAD of activating a local decode lane.
        # None (default) = the single-process engine, byte-identical to
        # every earlier round.
        self.prefill_sink = None
        # arrival timestamps (trailing window) — the scheduler's offered-
        # rate estimate, independent of any load harness
        self._arrivals: deque[float] = deque(maxlen=256)
        if scheduler is True:
            scheduler = SLOScheduler()
        self.scheduler = scheduler
        # round 17 (observability plane): an attached MetricsSampler is
        # ticked once per step (deterministic step-count clock). None
        # (default) = no sampler, zero overhead; a sampler that fails
        # degrades ITSELF (obs.sample site) — never the engine.
        self.sampler = None
        # round 18: the cross-request prefix index. The identity string
        # is folded into every chain key, so entries can never resolve
        # across a block-format or geometry change (the kv_dequant
        # degradation additionally clears the index outright).
        if prefix_cache:
            ident = (f"{self.pool.fmt.name}:{block_size}:"
                     f"{self.cfg['kv_heads']}x{self.cfg['head_dim']}:"
                     f"{np.dtype(self.embed_w.dtype).name}")
            self._prefix = PrefixCacheIndex(ident, block_size,
                                            max_blocks=prefix_cache_blocks)
        else:
            self._prefix = None
        # rid -> tokens resolved from the index at admission (handoff
        # manifests + tests read this; entries drop at finish)
        self._prefix_matched: dict[int, int] = {}
        self._m_pfx_hits = _metric("serving_prefix_hits_total")
        self._m_pfx_miss = _metric("serving_prefix_misses_total")
        self._m_pfx_saved = _metric("serving_prefix_tokens_saved_total")
        self._m_pfx_shared = _metric("serving_prefix_shared_blocks")
        self._m_pfx_evict = _metric("serving_prefix_evictions_total")
        self._m_pfx_cow = _metric("serving_prefix_cow_forks_total")
        # round 22: the multi-adapter (LoRA) store. None (default) keeps
        # the engine EXACTLY the storeless engine — no extra program
        # inputs, no adapter math in the compiled scans, byte-identical
        # everything. With a store attached, lanes carry an adapter_id
        # and the decode/prefill programs gather per-lane A/B factors
        # from the store's device pools (slot 0 = base, zeros).
        if adapters is not None:
            nh, nkv, hd = (self.cfg["heads"], self.cfg["kv_heads"],
                           self.cfg["head_dim"])
            H = nh * hd
            if (adapters.num_layers != L or adapters.hidden != H
                    or adapters.q_out != nh * hd
                    or adapters.v_out != nkv * hd):
                raise ValueError(
                    "AdapterStore dimensions do not match this model: "
                    f"store (L={adapters.num_layers}, H={adapters.hidden},"
                    f" q={adapters.q_out}, v={adapters.v_out}) vs model "
                    f"(L={L}, H={H}, q={nh * hd}, v={nkv * hd})")
        self.adapters = adapters

    # --- public API -------------------------------------------------------
    def add_request(self, prompt, max_new_tokens=32, eos_token_id=None,
                    do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                    seed=0, deadline_s=None, tenant="-",
                    priority="interactive", adapter=None):
        """Queue a request. `deadline_s` is a per-request wall-clock
        budget from arrival: once exceeded the request finishes with
        whatever it has and finish_reason='timeout'. `tenant` labels the
        request's per-tenant telemetry (bounded cardinality; unknown
        tenants past the cap collapse to 'overflow'). `priority` is the
        scheduling class (closed registry scheduler.PRIORITY_CLASSES:
        interactive / batch / best_effort) — only consulted when the
        engine has a scheduler. `adapter` names a LoRA adapter in the
        engine's AdapterStore (None = base model); a name the store
        cannot make resident at admission is a typed rejection
        (finish_reason='rejected'), never a base-weights fallback.
        Raises BackpressureError when the admission queue is at
        max_queue."""
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"unknown priority class {priority!r}; registered: "
                f"{list(PRIORITY_CLASSES)}")
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            _metric("serving_backpressure_total").inc()
            if self._rec.enabled:
                self._rec.record("backpressure", queue=len(self.queue),
                                 max_queue=self.max_queue)
            raise BackpressureError(
                f"admission queue full ({len(self.queue)}/{self.max_queue}); "
                "retry later")
        tenant = str(tenant) if tenant else "-"
        if tenant != "-" and tenant not in self._tenants:
            if len(self._tenants) >= self._max_tenants:
                tenant = "overflow"
            else:
                self._tenants.add(tenant)
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid, prompt, max_new_tokens, eos_token_id,
                      do_sample, temperature, top_k, top_p,
                      seed, deadline_s, tenant=tenant, priority=priority,
                      adapter=adapter)
        self.queue.append(req)
        self._arrivals.append(req.t_arrival)
        if self._tracer.enabled:
            # root of the request's span tree (instant: arrival moment)
            self._tracer.add_span("request.admit",
                                  int(req.t_arrival * 1e9), 0,
                                  trace_id=req.trace_id, args={"rid": rid})
        return rid

    def adopt_identity(self, rid, trace_id, t_arrival=None):
        """Adopt a mesh-level identity onto a still-queued request:
        spans, exemplars, and any handoff manifest join the mesh trace,
        and TTFT/deadline accounting stays anchored at TRUE arrival
        (router admission time, not replica enqueue time). Returns False
        when the rid already left the queue."""
        for req in self.queue:
            if req.rid == rid:
                req.trace_id = str(trace_id)
                if t_arrival is not None:
                    req.t_arrival = float(t_arrival)
                    if req.deadline_s is not None:
                        req.t_deadline = req.t_arrival + req.deadline_s
                return True
        return False

    def has_work(self):
        return (bool(self.queue) or any(r is not None for r in self.lanes)
                or bool(self._inflight) or bool(self._preempted))

    def run(self, max_steps=10_000):
        """Drive to completion; returns {rid: [generated tokens]}."""
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return {rid: r.generated for rid, r in self.finished.items()}

    # --- scheduling -------------------------------------------------------
    def step(self):
        ph = self._phases
        ph.begin_step()
        with _span("serving.step"):
            self._expire_deadlines()
            self._m_queue.set(len(self.queue))
            if self.scheduler is not None:
                # the closed-loop decision (brownout ladder + at most
                # one preemption); its wall time lands in the "admit"
                # phase — it IS admission policy
                self.scheduler.on_step(self)
            self._admit()
            ph.mark("admit")
            self._run_prefill_tasks()
            self._decode_phase()
            self._m_occ.set(sum(r is not None for r in self.lanes)
                            / self.max_batch)
            self._m_free.set(len(self.pool._free))
        ph.end_step()
        if self.sampler is not None:
            self.sampler.sample()

    def _decode_active(self):
        """Lanes the fused decode advances: occupied AND past prefill."""
        return [i for i, r in enumerate(self.lanes)
                if r is not None and i not in self._prefill_tasks]

    # --- graceful degradation --------------------------------------------
    def _finish(self, req, reason):
        # THE one finish path: req.finish_reason, the
        # serving_finished_total{reason} counter, the request.finish
        # span, and the flight-recorder event all derive from the same
        # `reason` argument here — they cannot disagree (test-pinned)
        req.done = True
        req.finish_reason = reason
        self._prefix_matched.pop(req.rid, None)
        self.finished[req.rid] = req
        _metric("serving_finished_total", reason=reason).inc()
        _metric("serving_tenant_finished_total",
                tenant=req.tenant, reason=reason).inc()
        if self._tracer.enabled:
            self._tracer.add_span("request.finish",
                                  time.perf_counter_ns(), 0,
                                  trace_id=req.trace_id,
                                  args={"rid": req.rid, "reason": reason,
                                        "tokens": len(req.generated)})
        if self._rec.enabled:
            self._rec.record("finish", rid=req.rid, reason=reason,
                             tokens=len(req.generated))

    def _adapter_release(self, req):
        """Drop the request's adapter reference (idempotent). The ref
        lifecycle mirrors the pool blocks exactly: acquired at
        admission, held across preempt/park (blocks stay resident),
        dropped wherever pool.release retires the request or a requeue
        will re-acquire at the next admission."""
        if self.adapters is not None and req.adapter_id:
            self.adapters.release(req.adapter_id)
        req.adapter_id = 0

    def _retire_lane(self, lane, reason):
        req = self.lanes[lane]
        self._prefill_tasks.pop(lane, None)
        self.pool.release(req.rid)
        self._adapter_release(req)
        self.lanes[lane] = None
        self.lane_len[lane] = 0
        self._lane_epoch[lane] += 1
        self._dirty = True
        self._m_retired.inc()
        self._finish(req, reason)

    def _expire_deadlines(self):
        """Per-request deadlines: an expired queued request finishes
        empty; an expired decoding (or prefilling) lane finishes with the
        tokens it has (a degraded-but-distinguishable completion) and its
        pool blocks are released."""
        now = time.perf_counter()
        if any(r.t_deadline is not None and now >= r.t_deadline
               for r in self.queue):
            kept = deque()
            for req in self.queue:
                if req.t_deadline is not None and now >= req.t_deadline:
                    _metric("serving_timeouts_total", where="queue").inc()
                    if self._rec.enabled:
                        self._rec.record("timeout", rid=req.rid,
                                         where="queue")
                    self._finish(req, "timeout")
                else:
                    kept.append(req)
            self.queue = kept
        for lane, req in enumerate(self.lanes):
            if (req is not None and req.t_deadline is not None
                    and now >= req.t_deadline):
                _metric("serving_timeouts_total", where="decode").inc()
                if self._rec.enabled:
                    self._rec.record("timeout", rid=req.rid, where="decode")
                self._retire_lane(lane, "timeout")
        # parked (preempted) requests keep their deadline: one that
        # expires before a lane frees up finishes with the tokens it has
        # and releases its still-resident pool blocks
        for rid in [rid for rid, (req, _ln, _tok)
                    in self._preempted.items()
                    if req.t_deadline is not None
                    and now >= req.t_deadline]:
            req, _ln, _tok = self._preempted.pop(rid)
            self.pool.release(rid)
            self._adapter_release(req)
            _metric("serving_timeouts_total", where="preempted").inc()
            if self._rec.enabled:
                self._rec.record("timeout", rid=rid, where="preempted")
            self._m_retired.inc()
            self._finish(req, "timeout")

    def cancel(self, rid):
        """Withdraw one request wherever it lives (queued, decoding, or
        parked) WITHOUT producing a finished record: the caller already
        has the stream's outcome from somewhere else (a hedge sibling
        that committed first, or an RPC the client gave up on before the
        reply landed). Pool blocks release; nothing reaches `finished`,
        so the router's commit map never sees a duplicate. Returns
        whether anything was withdrawn."""
        rid = int(rid)
        for i, req in enumerate(self.queue):
            if req.rid == rid:
                del self.queue[i]
                break
        else:
            for lane, req in enumerate(self.lanes):
                if req is not None and req.rid == rid:
                    self._prefill_tasks.pop(lane, None)
                    self.pool.release(rid)
                    self._adapter_release(req)
                    self.lanes[lane] = None
                    self.lane_len[lane] = 0
                    self._lane_epoch[lane] += 1
                    self._dirty = True
                    break
            else:
                if rid not in self._preempted:
                    return False
                req, _ln, _tok = self._preempted.pop(rid)
                self.pool.release(rid)
                self._adapter_release(req)
        self._prefix_matched.pop(rid, None)
        if self._rec.enabled:
            self._rec.record("sched", action="cancel", rid=rid)
        return True

    def _shed(self, active):
        """Decode OOM: preempt the lane with the least work done (fewest
        generated tokens), release its blocks, and requeue the request at
        the FRONT of the queue for a fresh prefill. A request shed more
        than max_sheds times finishes degraded (finish_reason='shed')
        instead of thrashing the pool forever."""
        self._dirty = True
        if not active:
            return
        victim = max(active,
                     key=lambda i: (-len(self.lanes[i].generated), i))
        req = self.lanes[victim]
        self.pool.release(req.rid)
        self._adapter_release(req)
        self.lanes[victim] = None
        self.lane_len[victim] = 0
        self._lane_epoch[victim] += 1
        req.shed_count += 1
        _metric("serving_shed_total").inc()
        if self._rec.enabled:
            self._rec.record("shed", rid=req.rid, lane=victim,
                             sheds=req.shed_count)
        if req.shed_count > self.max_sheds:
            self._m_retired.inc()
            self._finish(req, "shed")
            return
        # restart from the prompt next admission: the KV blocks are gone,
        # and greedy decode reproduces the same prefix deterministically
        # (sampled lanes re-derive the same stream from (seed, position))
        req.generated = []
        self.queue.appendleft(req)

    # --- priority preemption (round 14) ----------------------------------
    def _try_preempt(self, lane, why="slo"):
        """Park a decode-active lane so a higher-priority request can
        take it. Unlike _shed, the paged-KV blocks STAY resident and the
        host decode cursor (lane_len / lane_tok) is saved: resuming is a
        lane-state re-upload through the membership-change path, so the
        stream continues byte-identically (greedy is deterministic;
        sampled lanes key the device PRNG on absolute position). Any
        tokens of the lane still in a dropped in-flight tile are
        regenerated identically after resume — the epoch bump below
        prevents double-crediting. Returns False when the lane is not
        preemptible (empty / still prefilling) or the serve.preempt
        fault site fires: a failed preemption aborts cleanly and the
        victim keeps decoding."""
        req = self.lanes[lane]
        if req is None or lane in self._prefill_tasks:
            return False
        try:
            fault_point("serve.preempt", rid=req.rid, lane=lane)
        except _TRANSIENT_ERRORS:
            _metric("serving_deferred_total", reason="preempt_fault").inc()
            return False
        self._preempted[req.rid] = (req, int(self.lane_len[lane]),
                                    int(self.lane_tok[lane]))
        self.lanes[lane] = None
        self.lane_len[lane] = 0
        self._lane_epoch[lane] += 1
        self._dirty = True
        _metric("serving_preempted_total").inc()
        _metric("serving_preemptions_total",
                **{"class": req.priority}).inc()
        if self._rec.enabled:
            self._rec.record("sched", action="preempt", rid=req.rid,
                             lane=lane, why=why,
                             tokens=len(req.generated))
        if self._tracer.enabled:
            self._tracer.add_span("request.preempt",
                                  time.perf_counter_ns(), 0,
                                  trace_id=req.trace_id,
                                  args={"rid": req.rid, "why": why})
        return True

    def _resume_preempted(self):
        """Re-admit parked requests into free lanes (oldest first). The
        pool blocks never left, so this is just the host mirror restore
        + an epoch bump; the next _decode_phase re-uploads lane state
        and the stream picks up exactly where it was parked."""
        for rid in list(self._preempted):
            lane = next((i for i, r in enumerate(self.lanes)
                         if r is None and i not in self._prefill_tasks),
                        None)
            if lane is None:
                return
            req, lane_len, lane_tok = self._preempted.pop(rid)
            self.lanes[lane] = req
            self.lane_len[lane] = lane_len
            self.lane_tok[lane] = lane_tok
            self._lane_epoch[lane] += 1
            self._dirty = True
            if self._rec.enabled:
                self._rec.record("sched", action="resume", rid=rid,
                                 lane=lane, tokens=len(req.generated))

    # --- disaggregated paged-KV handoff (round 16) -----------------------
    def export_kv(self, req, first_tok):
        """Handoff record for a just-prefilled request: the prompt's
        paged-KV blocks in the pool's RAW storage representation
        (payload + scales when quantized) plus everything the decode
        side needs to continue the stream byte-identically. Copying
        stored bytes — not dequantized values — makes the round trip
        exact for native and quantized block formats alike; the device
        PRNG keys on (sample_seed, absolute position), so sampled
        streams survive the hop too."""
        s = int(req.prompt.size)
        nb = self.pool.blocks_needed(s)
        ids = jnp.asarray(self.pool.tables[req.rid][:nb], jnp.int32)
        rec = {
            "version": 1,
            "fmt": self.pool.fmt.name,
            "prompt": np.asarray(req.prompt, np.int32),
            "first_token": int(first_tok),
            "max_new_tokens": int(req.max_new_tokens),
            "eos_token_id": req.eos_token_id,
            "do_sample": bool(req.do_sample),
            "temperature": float(req.temperature),
            "top_k": int(req.top_k),
            "top_p": float(req.top_p),
            "sample_seed": int(req.sample_seed),
            "tenant": req.tenant,
            "priority": req.priority,
            "trace_id": req.trace_id,
            "t_arrival": float(req.t_arrival),
            "t_first": None if req.t_first is None else float(req.t_first),
            "deadline_s": req.deadline_s,
            # prefix-cache manifest (round 18): how much of this prompt
            # was resolved from the sender's index and how many of the
            # exported blocks are refcount-shared there. The payload
            # below is a COPY either way — the receiver re-owns (and
            # re-indexes) the blocks privately.
            "prefix_matched_tokens": int(
                self._prefix_matched.get(req.rid, 0)),
            "prefix_shared_blocks": int(self.pool.shared_count(req.rid)),
            # adapter identity rides the record as scalar meta (round 22):
            # the importer must bind the SAME adapter or reject the
            # handoff — silently continuing on base weights would change
            # the stream mid-request.
            "adapter": req.adapter,
            "k": np.asarray(self.pool.k[:, ids]),
            "v": np.asarray(self.pool.v[:, ids]),
        }
        if self.pool.fmt.quantized:
            rec["k_scale"] = np.asarray(self.pool.k_scale[:, ids])
            rec["v_scale"] = np.asarray(self.pool.v_scale[:, ids])
        return rec

    def import_kv(self, record):
        """Install a handed-off prefill on THIS engine: reserve the full
        sequence footprint, write the stored block payload verbatim, and
        park the request through the preemption path — resuming is the
        same lane-state re-upload as a preempt/resume, so the stream
        continues exactly where the prefill worker left it (no
        re-prefill, no host recompute). Returns the local rid. Raises
        ValueError on a block-format mismatch and KVPoolExhaustedError
        (via pool.ensure) when the blocks do not fit — callers treat
        both as a failed handoff and fall back to re-prefill."""
        if record["fmt"] != self.pool.fmt.name:
            raise ValueError(
                f"handoff block format {record['fmt']!r} != pool format "
                f"{self.pool.fmt.name!r}; mesh replicas must share "
                "kv_cache_dtype")
        adapter = record.get("adapter") or None
        if adapter is not None and (
                self.adapters is None
                or not self.adapters.can_serve(adapter)):
            # rides the failed-handoff fallback (ValueError): the router
            # re-prefills on a replica that CAN serve the adapter rather
            # than silently continuing the stream on base weights
            raise ValueError(
                f"handoff names adapter {adapter!r} which this engine "
                "cannot serve (no store or unregistered adapter)")
        prompt = np.asarray(record["prompt"], np.int32).reshape(-1)
        s = int(prompt.size)
        total = s + int(record["max_new_tokens"])
        if total > self.max_blocks_per_seq * self.pool.block_size:
            raise ValueError("handoff exceeds the per-sequence block "
                             "budget of the receiving engine")
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid, prompt, record["max_new_tokens"],
                      record["eos_token_id"], record["do_sample"],
                      record["temperature"], record["top_k"],
                      record["top_p"], seed=None,
                      tenant=record["tenant"],
                      priority=record["priority"],
                      adapter=adapter)
        # stream identity crosses the hop unchanged: trace id (span
        # joins), PRNG lane key (sampled decode continuity), arrival +
        # deadline anchors (TTFT/e2e stay measured from true arrival)
        req.trace_id = record["trace_id"]
        req.sample_seed = np.uint32(record["sample_seed"] & 0xFFFFFFFF)
        req.t_arrival = record["t_arrival"]
        req.t_first = record.get("t_first")
        if record.get("deadline_s") is not None:
            req.deadline_s = float(record["deadline_s"])
            req.t_deadline = req.t_arrival + req.deadline_s
        first_tok = int(record["first_token"])
        req.generated = [first_tok]
        self._m_admitted.inc()
        self._m_tokens.inc()        # the handed-off first token
        if (req.eos_token_id is not None and first_tok == req.eos_token_id) \
                or req.max_new_tokens <= 1:
            # the prefill worker's first token already ended the stream:
            # nothing to decode, no blocks needed
            reason = ("eos" if req.eos_token_id is not None
                      and first_tok == req.eos_token_id else "length")
            self._m_retired.inc()
            self._finish(req, reason)
            return rid
        self.pool.ensure(rid, total)
        if req.adapter:
            try:
                req.adapter_id = self.adapters.acquire(req.adapter)
            except (AdapterLoadError,) + _TRANSIENT_ERRORS as e:
                # treated like any other failed handoff: give the blocks
                # back and let the caller fall back to re-prefill
                self.pool.release(rid)
                raise ValueError(
                    f"handoff adapter {req.adapter!r} failed to "
                    f"hot-load on the receiving engine: {e}") from e
        nb = self.pool.blocks_needed(s)
        ids = jnp.asarray(self.pool.tables[rid][:nb], jnp.int32)
        self.pool.k = self.pool.k.at[:, ids].set(
            jnp.asarray(record["k"], self.pool.k.dtype))
        self.pool.v = self.pool.v.at[:, ids].set(
            jnp.asarray(record["v"], self.pool.v.dtype))
        if self.pool.fmt.quantized:
            self.pool.k_scale = self.pool.k_scale.at[:, ids].set(
                jnp.asarray(record["k_scale"], self.pool.k_scale.dtype))
            self.pool.v_scale = self.pool.v_scale.at[:, ids].set(
                jnp.asarray(record["v_scale"], self.pool.v_scale.dtype))
        # a handed-off prompt seeds THIS engine's prefix index too: the
        # next local request with the same prefix shares these blocks.
        # Same degrade-to-unindexed contract as the prefill-side insert.
        if self._prefix is not None:
            try:
                fault_point("serve.prefix_match", rid=rid)
                for b in self._prefix.insert(prompt,
                                             self.pool.tables[rid]):
                    self.pool.pin(b)
                for b in self._prefix.trim():
                    self.pool.unpin(b)
                    self._m_pfx_evict.inc()
                self._m_pfx_shared.set(len(self._prefix))
            except _TRANSIENT_ERRORS:
                _metric("serving_runtime_degradations_total",
                        what="prefix_miss").inc()
        # park exactly like a preempted lane: (req, cached length, next
        # token). _resume_preempted + the next lane-state upload then
        # continue decode with no further handoff-specific machinery.
        self._preempted[rid] = (req, s, first_tok)
        self._dirty = True
        return rid

    # --- admission / chunked prefill -------------------------------------
    def _admit(self):
        """Reserve lanes + pool blocks for queued requests; the prompts
        themselves prefill chunk-by-chunk in _run_prefill_tasks so a long
        admission never head-of-line-blocks the decode lanes. With a
        scheduler attached, parked (preempted) requests resume first
        when the scheduler allows, and queue order comes from its
        priority-class + tenant-DRR pick instead of FIFO."""
        if self._preempted and (self.scheduler is None
                                or self.scheduler.should_resume(self)):
            self._resume_preempted()
        while self.queue:
            free_lanes = [i for i, r in enumerate(self.lanes) if r is None]
            if not free_lanes:
                return
            if self.scheduler is not None:
                idx = self.scheduler.pick_index(self)
                if idx is None:
                    return
            else:
                idx = 0
            req = self.queue[idx]
            if (self.scheduler is not None
                    and self.scheduler.shed_best_effort
                    and req.priority == "best_effort"):
                # deepest brownout rung: best_effort is not served at
                # all; a typed, counted shed — not a silent drop
                del self.queue[idx]
                req.generated = []
                _metric("serving_shed_total").inc()
                if self._rec.enabled:
                    self._rec.record("sched", action="shed_best_effort",
                                     rid=req.rid)
                self._finish(req, "shed")
                continue
            if self._mnt_cap is not None \
                    and req.max_new_tokens > self._mnt_cap:
                # cap_max_new_tokens rung: reshape the admitted budget —
                # the stream still serves, just shorter. Capped at
                # admission so already-running streams keep theirs, and
                # a request admitted under brownout keeps the cap even
                # after recovery (budget decisions are admission-final).
                req.max_new_tokens = self._mnt_cap
                if self._rec.enabled:
                    self._rec.record("sched", action="cap_max_new_tokens",
                                     rid=req.rid, cap=self._mnt_cap)
            total = req.prompt.size + req.max_new_tokens
            if total > self.max_blocks_per_seq * self.pool.block_size:
                # cannot ever serve: reject with an empty result instead
                # of crashing the engine mid-step (prompts longer than
                # the largest bucket are now served via chunking; only
                # the per-sequence block budget is a hard wall)
                del self.queue[idx]
                req.generated = []
                self._finish(req, "rejected")
                _metric("serving_rejected_total", reason="oversized").inc()
                continue
            if req.max_new_tokens <= 0:
                del self.queue[idx]
                self._finish(req, "length")
                continue
            # prefix-cache lookup (round 18): resolve the prompt's
            # leading block-aligned chunks to already-resident shared
            # blocks. ANY index failure is a plain cache miss — full
            # prefill, byte-identical stream, never a wrong answer
            # (the serve.prefix_match contract, chaos-drilled).
            matched, m_tok = [], 0
            s = int(req.prompt.size)
            if self._prefix is not None:
                try:
                    fault_point("serve.prefix_match", rid=req.rid)
                    matched, m_tok = self._prefix.lookup(req.prompt)
                except _TRANSIENT_ERRORS:
                    matched, m_tok = [], 0
                    _metric("serving_runtime_degradations_total",
                            what="prefix_miss").inc()
                    if self._rec.enabled:
                        self._rec.record("degrade", what="prefix_miss",
                                         rid=req.rid)
            # a block-aligned FULL-prompt match must still prefill the
            # final position (the first token samples from full-prompt
            # logits) — that one write lands inside the last shared
            # block, so the admission below forks it (copy-on-write)
            need_fork = matched and m_tok >= s
            if need_fork:
                m_tok = s - 1
            # admit only if the WHOLE sequence fits: no mid-flight
            # eviction of LIVE requests (the reference engine preempts;
            # we keep the no-surprise contract) — but index-only blocks
            # are reclaimable cache, so LRU-evict those before deferring
            have = len(matched) - (1 if need_fork else 0)

            def _fits():
                # 1-arg call when nothing matched: the pre-round-18
                # can_fit signature is a test-pinned monkeypatch seam
                return (self.pool.can_fit(total, have) if have
                        else self.pool.can_fit(total))

            if not _fits() and self._prefix is not None:
                protect = frozenset(matched)
                while not _fits():
                    b = self._prefix.evict(protect)
                    if b is None:
                        break
                    self.pool.unpin(b)
                    self._m_pfx_evict.inc()
                self._m_pfx_shared.set(len(self._prefix))
            if not _fits():
                _metric("serving_deferred_total", reason="pool_full").inc()
                return
            del self.queue[idx]
            # adapter binding (round 22): make the named adapter
            # resident and validate the slot the lanes will gather from
            # before the pool reservation. ANY store failure — unknown
            # name, slots pinned, injected serve.adapter_load /
            # serve.adapter_gather fault — is a typed rejection: the
            # one forbidden outcome is serving the stream with the
            # wrong weights. Other lanes never notice (their slots are
            # untouched).
            req.adapter_id = 0
            if req.adapter:
                try:
                    fault_point("serve.adapter_load", rid=req.rid,
                                adapter=req.adapter)
                    if self.adapters is None:
                        raise AdapterLoadError(
                            f"request names adapter {req.adapter!r} but "
                            "the engine has no AdapterStore attached")
                    req.adapter_id = self.adapters.acquire(req.adapter)
                    fault_point("serve.adapter_gather", rid=req.rid,
                                slot=req.adapter_id)
                    self.adapters.check_resident(req.adapter_id)
                except (AdapterLoadError,) + _TRANSIENT_ERRORS:
                    self._adapter_release(req)
                    req.generated = []
                    self._finish(req, "rejected")
                    _metric("serving_rejected_total",
                            reason="adapter").inc()
                    _metric("serving_adapter_load_failures_total").inc()
                    if self._rec.enabled:
                        self._rec.record("adapter", action="reject",
                                         rid=req.rid,
                                         adapter=req.adapter)
                    continue
            lane = free_lanes[0]
            try:
                fault_point("serve.admit", rid=req.rid)
                # reserve the FULL footprint now — lazy per-step
                # allocation could exhaust the pool mid-decode across
                # admitted sequences, which the can_fit gate above
                # promised cannot happen. Matched prefix blocks are
                # adopted (refcount +1) ahead of the fresh-tail ensure.
                if matched:
                    self.pool.adopt(req.rid, matched)
                    if need_fork:
                        self.pool.fork_cow(req.rid, len(matched) - 1)
                        self._m_pfx_cow.inc()
                self.pool.ensure(req.rid, total)
            except MemoryError:
                # pool exhausted despite the can_fit gate (e.g. blocks
                # held by an out-of-band allocation): surface as a counted
                # deferral, give back any partial reservation, and leave
                # the request AT THE FRONT of the queue — never let the
                # scheduler step die mid-flight
                self.pool.release(req.rid)
                self._adapter_release(req)
                self.queue.appendleft(req)
                _metric("serving_deferred_total",
                        reason="pool_exhausted").inc()
                return
            except _TRANSIENT_ERRORS:
                # transient admission failure (store/IO blip or injected
                # fault): same counted-deferral contract — requeued at
                # the front, retried next step, scheduler stays alive
                self.pool.release(req.rid)
                self._adapter_release(req)
                self.queue.appendleft(req)
                _metric("serving_deferred_total",
                        reason="admit_fault").inc()
                return
            if self._prefix is not None:
                if m_tok > 0:
                    self._m_pfx_hits.inc()
                    self._m_pfx_saved.inc(m_tok)
                    self._prefix_matched[req.rid] = m_tok
                    if self._rec.enabled:
                        self._rec.record("prefix_hit", rid=req.rid,
                                         tokens=m_tok,
                                         blocks=len(matched))
                else:
                    self._m_pfx_miss.inc()
            self.lanes[lane] = req
            self._lane_epoch[lane] += 1
            # prefill covers ONLY the unmatched tail: the chunk plan
            # starts at the first token the index could not resolve
            self._prefill_tasks[lane] = _PrefillTask(
                req, lane, self._chunk_plan(req.prompt.size, m_tok))
            if self._tracer.enabled:
                t0 = int(req.t_arrival * 1e9)
                self._tracer.add_span(
                    "request.queued", t0, time.perf_counter_ns() - t0,
                    trace_id=req.trace_id, tid=LANE_TID_BASE + lane,
                    tid_name=f"lane {lane}", args={"rid": req.rid})
            if self._rec.enabled:
                self._rec.record("admit", rid=req.rid, lane=lane,
                                 epoch=int(self._lane_epoch[lane]))

    def _chunk_plan(self, s, start=0):
        """(start, width) pieces covering tokens [start, s) of a prompt:
        full chunks, then a tail padded to the smallest chunk width that
        fits. A non-zero start is a prefix-cache hit — the matched head
        is already resident and never recomputed."""
        pieces = []
        while s - start > self.chunk:
            pieces.append((start, self.chunk))
            start += self.chunk
        rem = s - start
        width = next(w for w in self._chunk_widths if w >= rem)
        pieces.append((start, width))
        return pieces

    def _run_prefill_tasks(self):
        """Advance every in-flight prefill by up to
        prefill_chunks_per_step chunks (all remaining chunks when no
        lane is decoding — there is no one to block)."""
        if not self._prefill_tasks:
            return
        decode_busy = bool(self._decode_active())
        for lane in sorted(self._prefill_tasks):
            task = self._prefill_tasks.get(lane)
            if task is None:
                continue
            budget = (self.prefill_chunks_per_step if decode_busy
                      else len(task.pieces) - task.idx)
            try:
                with _span("serving.prefill", rid=task.req.rid,
                           prompt=int(task.req.prompt.size)):
                    for _ in range(max(1, budget)):
                        if self._prefill_one_chunk(task):
                            break
            except MemoryError:
                self._abort_prefill(task, "prefill_oom")
                return
            except _TRANSIENT_ERRORS:
                self._abort_prefill(task, "prefill_fault")
                return

    def _abort_prefill(self, task, reason):
        """A chunk failed: give back the blocks + lane and requeue the
        request at the front for a fresh prefill next step."""
        self.pool.release(task.req.rid)
        self._adapter_release(task.req)
        self.lanes[task.lane] = None
        self.lane_len[task.lane] = 0
        self._lane_epoch[task.lane] += 1
        self._prefill_tasks.pop(task.lane, None)
        self.queue.appendleft(task.req)
        _metric("serving_deferred_total", reason=reason).inc()

    def _prefill_one_chunk(self, task):
        """Run one chunk forward; on the final chunk, sample the first
        token and activate the lane. Returns True when the task is
        done."""
        req = task.req
        start, width = task.pieces[task.idx]
        s = req.prompt.size
        fault_point("serve.prefill_chunk", rid=req.rid, start=start)
        fn = self._prefill_jit.get(width)
        if fn is None:
            # engine warm-start: prefill programs compile through the PIR
            # pipeline — pattern-rewritten pre-XLA and, with
            # FLAGS_compile_cache_dir set, warm-loaded from the
            # persistent compile cache instead of paying the cold XLA
            # compile
            from ..pir import pir_jit
            fn = pir_jit(self._make_prefill_chunk(),
                         name=f"serving.prefill.b{width}",
                         extra_key=({"lora": self.adapters.program_key}
                                    if self.adapters is not None else None))
            self._prefill_jit[width] = fn
            self.compile_reports[f"prefill.b{width}"] = None
            # program construction counts as a retrace: the hot-swap
            # contract pins this counter's delta to 0 across adapter churn
            _metric("jit_retrace_total").inc()
        cold = fn._compiled is None     # first call traces + compiles
        n_real = min(width, s - start)
        ids = np.zeros((1, width), np.int32)
        ids[0, :n_real] = req.prompt[start:start + n_real]
        table = np.full(self.max_blocks_per_seq, self.pool.scratch_block,
                        np.int32)
        t = self.pool.tables[req.rid]
        table[:len(t)] = t
        is_final = task.idx == len(task.pieces) - 1
        last_idx = (s - 1 - start) if is_final else 0
        args = [self.stacked, self.embed_w, self.norm_w, self._out_w,
                self.pool.k, self.pool.v]
        if self.pool.fmt.quantized:
            args += [self.pool.k_scale, self.pool.v_scale]
        args += [jnp.asarray(ids), jnp.int32(start), jnp.int32(last_idx),
                 jnp.asarray(table)]
        if self.adapters is not None:
            ad = self.adapters
            args += [ad.A_q, ad.B_q, ad.A_v, ad.B_v,
                     jnp.int32(req.adapter_id)]
        t0 = time.perf_counter()
        out = fn(*args)
        if self.pool.fmt.quantized:
            (logits, self.pool.k, self.pool.v,
             self.pool.k_scale, self.pool.v_scale) = out
        else:
            logits, self.pool.k, self.pool.v = out
        dt = time.perf_counter() - t0
        self._m_prefill.observe(dt)
        self._m_chunks.inc()
        if self._phases.enabled:
            self._phases.mark("compile" if cold else "prefill.chunk",
                              tenant=req.tenant)
        if not cold:        # a cold call's wall is compile, not the program
            self._cost_observe(f"prefill.b{width}", dt)
        if self._tracer.enabled:
            self._tracer.add_span(
                "request.prefill.chunk", int(t0 * 1e9), int(dt * 1e9),
                trace_id=req.trace_id, tid=LANE_TID_BASE + task.lane,
                tid_name=f"lane {task.lane}",
                args={"rid": req.rid, "chunk": task.idx, "width": width})
        if self.compile_reports.get(f"prefill.b{width}") is None:
            self.compile_reports[f"prefill.b{width}"] = \
                getattr(fn, "report", None)
        task.idx += 1
        if not is_final:
            return False
        # final chunk: first token on the host (once per request), lane
        # becomes decode-active -> membership change
        first_tok = req.choose(np.asarray(logits).reshape(-1))
        lane = task.lane
        self._prefill_tasks.pop(lane, None)
        # the exemplar ties this observation's bucket to the exact trace
        # that produced it (bad p99 -> exact request)
        ttft = time.perf_counter() - req.t_arrival
        req.t_first = req.t_arrival + ttft
        self._m_ttft.observe(ttft, exemplar=req.trace_id)
        _metric("serving_tenant_ttft_seconds",
                tenant=req.tenant).observe(ttft)
        if self.adapters is not None:
            _metric("serving_adapter_ttft_seconds",
                    adapter=req.adapter or "base").observe(ttft)
        if self.scheduler is not None:
            self.scheduler.note_ttft(ttft)
        # index the request's full-prompt blocks for the NEXT sharer
        # (before the sink path below releases the request's own refs —
        # the index pin is what keeps a prefix resident). Failures
        # degrade to "not indexed": streams are never affected.
        if self._prefix is not None:
            try:
                fault_point("serve.prefix_match", rid=req.rid)
                for b in self._prefix.insert(req.prompt,
                                             self.pool.tables[req.rid]):
                    self.pool.pin(b)
                for b in self._prefix.trim():
                    self.pool.unpin(b)
                    self._m_pfx_evict.inc()
                self._m_pfx_shared.set(len(self._prefix))
            except _TRANSIENT_ERRORS:
                _metric("serving_runtime_degradations_total",
                        what="prefix_miss").inc()
                if self._rec.enabled:
                    self._rec.record("degrade", what="prefix_miss",
                                     rid=req.rid)
        if self.prefill_sink is not None:
            # disaggregated prefill worker: serialize the prompt's KV
            # state and hand the stream to the decode pool. The lane +
            # blocks free immediately; admitted/token accounting happens
            # exactly once mesh-wide, on the decode engine's import.
            record = self.export_kv(req, first_tok)
            if self._phases.enabled:   # export = device->host KV readback
                self._phases.mark("hostsync", tenant=req.tenant)
            self.pool.release(req.rid)
            self._adapter_release(req)
            self._prefix_matched.pop(req.rid, None)
            self.lanes[lane] = None
            self.lane_len[lane] = 0
            self._lane_epoch[lane] += 1
            self._dirty = True
            self.prefill_sink(record)
            return True
        self.lane_len[lane] = s
        self.lane_tok[lane] = first_tok
        self._dirty = True
        self._m_admitted.inc()
        self._emit(lane, first_tok)
        return True

    def _emit(self, lane, token):
        req = self.lanes[lane]
        req.generated.append(int(token))
        self._m_tokens.inc()
        if (req.eos_token_id is not None
                and int(token) == req.eos_token_id):
            self._retire_lane(lane, "eos")
        elif len(req.generated) >= req.max_new_tokens:
            self._retire_lane(lane, "length")

    # --- fused decode: dispatch / overlap / drain -------------------------
    def _decode_phase(self):
        """Double-buffered fused decode: dispatch tile N+1, then read
        back + book-keep tile N while the device computes. Membership
        changes force a drain + lane-state re-upload (the only time
        numpy touches the device state)."""
        if self.compat_step_loop:
            self._dirty = True      # pre-fused loop: re-upload every step
        # round-11 degradation sites fire BEFORE the drain/upload
        # decision so the membership machinery below drains any in-flight
        # tile (under its dispatch-time variant) before the lane-state
        # re-upload switches programs — a mid-flight rewind would
        # double-emit the tile's tokens
        if self.spec:
            try:
                fault_point("serve.draft_verify", depth=self.draft_depth)
            except _TRANSIENT_ERRORS:
                self._disable_spec("draft_verify_fault")
        if self.pool.fmt.quantized:
            try:
                fault_point("serve.kv_dequant", fmt=self.pool.fmt.name)
            except _TRANSIENT_ERRORS:
                self._degrade_kv_to_bf16()
        active = self._decode_active()
        if not active:
            if self._inflight:
                self._drain_all()
            return
        if self._inflight and (self._dirty
                               or self._inflight[-1].covers_all
                               or len(self._inflight) >= 2):
            if not self._drain_all():
                return                 # transient host-sync fault: retry
            active = self._decode_active()
            if not active:
                return
        if self._dirty or self._dev is None:
            self._upload_lane_state(active)
            self._phases.mark("lane_upload")
        t0 = time.perf_counter()
        try:
            fault_point("serve.decode_oom", active=len(active))
            with _span("serving.decode_step", active=len(active),
                       k=self.decode_steps):
                tile = self._dispatch()
        except MemoryError:
            # device OOM (or the serve.decode_oom fault site): shed one
            # lane and requeue it rather than killing every in-flight
            # request; the remaining lanes decode on the next step
            self._shed(active)
            return
        except Exception as e:  # noqa: BLE001 — XLA OOM is backend-typed
            if "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e):
                self._shed(active)
                return
            raise
        self._m_dispatches.inc()
        self._m_ahead.set(len(self._inflight))
        K = self.decode_steps
        prev_reqs = self._inflight[-1].reqs if self._inflight else None
        covers_all = all(
            (self.lanes[i].max_new_tokens - len(self.lanes[i].generated)
             - (K if prev_reqs is not None and prev_reqs[i]
                is self.lanes[i] else 0)) <= K
            for i in active)
        # snapshot only DECODE-ACTIVE lanes: a lane that is occupied but
        # still prefilling was masked dead on device — its tile row is
        # filler and must never be credited
        active_set = set(active)
        snap = [self.lanes[i] if i in active_set else None
                for i in range(self.max_batch)]
        tile_id = self._tile_seq
        self._tile_seq += 1
        d_variant = self._dev["variant"]
        key = ("decode" + (".sampled" if d_variant.startswith("sampled")
                           else "") + (".spec" if d_variant.endswith(".spec")
                                       else ""))
        self._inflight.append(_Inflight(
            tile, t0, snap, self._lane_epoch.copy(), K, covers_all,
            tile_id, spec=isinstance(tile, tuple), key=key))
        if self._rec.enabled:
            self._rec.record("dispatch", tile=tile_id, lanes=list(active),
                             epochs=[int(self._lane_epoch[i])
                                     for i in active], k=K)
        # overlapped host bookkeeping: process the PREVIOUS tile while
        # the device runs this one (compat mode drains its own tile too:
        # the old engine blocked on every token)
        keep = 0 if self.compat_step_loop else 1
        while len(self._inflight) > keep:
            if not self._drain_one():
                break

    def _disable_spec(self, why):
        """serve.draft_verify degradation: permanently fall back to the
        non-speculative fused decode. Streams continue byte-identically
        (speculation never changes the committed tokens); only the
        tokens-per-dispatch multiplier is lost. Unlike the brownout
        ladder's reversible switch, this is permanent: _spec_allowed
        goes False so a later brownout recovery cannot re-enable a
        faulted drafter."""
        self.spec = False
        self._spec_allowed = False
        _metric("serving_runtime_degradations_total",
                what="speculation_off").inc()
        if self._rec.enabled:
            self._rec.record("degrade", what="speculation_off", why=why)
        # _decode_phase drains in-flight tiles (flagged spec per-tile)
        # before honoring _dirty, so no committed token is re-emitted
        self._dirty = True

    def _degrade_kv_to_bf16(self):
        """serve.kv_dequant degradation: dequantize the WHOLE pool to the
        native dtype once (timed into serving_kv_dequant_seconds) and
        drop the quantized block format for the engine's lifetime. Every
        compiled program embedded the quantized pool dtypes, so the jit
        caches are cleared and programs recompile against the bf16 pool."""
        t0 = time.perf_counter()
        fmt = self.pool.fmt
        self.pool.k = fmt.decode(self.pool.k, self.pool.k_scale)
        self.pool.v = fmt.decode(self.pool.v, self.pool.v_scale)
        self.pool.k_scale = self.pool.v_scale = None
        self.pool.fmt = KVBlockFormat("native",
                                      native_dtype=self.embed_w.dtype)
        # the prefix index promised the OLD byte layout: every entry is
        # stale the instant the pool re-encodes, so drop them all (the
        # blocks free once no resident request still holds them)
        if self._prefix is not None:
            for b in self._prefix.clear():
                self.pool.unpin(b)
                self._m_pfx_evict.inc()
            self._m_pfx_shared.set(0)
        self._prefill_jit.clear()
        self._decode_jit.clear()
        _metric("serving_kv_dequant_seconds").observe(
            time.perf_counter() - t0)
        _metric("serving_runtime_degradations_total", what="kv_bf16").inc()
        if self._rec.enabled:
            self._rec.record("degrade", what="kv_bf16", fmt=fmt.name)

    # --- brownout knobs (round 14) ---------------------------------------
    # The ladder's setters are REVERSIBLE, unlike the fault degradations
    # above: they only flip the knob and mark lane state dirty. The
    # membership machinery drains any in-flight tile under its dispatch-
    # time program before the next dispatch compiles/reuses the new
    # (variant, K, D)-keyed program — so a mid-flight knob change can
    # never double-emit or drop a token, and byte-identity is exactly
    # the already-pinned across-K stream invariance.
    def _set_decode_steps(self, k):
        k = 1 if self.compat_step_loop else max(1, int(k))
        if k == self.decode_steps:
            return
        self.decode_steps = k
        self._dirty = True

    def _set_draft_depth(self, d):
        d = max(1, min(int(d), self.pool.block_size - 1))
        if d == self.draft_depth:
            return
        self.draft_depth = d
        self._dirty = True

    def _set_speculation(self, on):
        want = bool(on) and self._spec_allowed \
            and not self.compat_step_loop
        if want == self.spec:
            return
        self.spec = want
        self._dirty = True

    def _set_prefill_chunk_small(self, on):
        # force_small_prefill_chunk rung: future admissions plan their
        # prefill at the smallest compiled chunk width so each piece
        # holds the dispatch for the shortest possible time. No _dirty:
        # chunk planning is host-side at admission and every width in
        # _chunk_widths is already a compiled bucket. Plans already
        # issued are unchanged (admission-scoped, like every knob).
        self.chunk = self._chunk_widths[0] if on else self._base_chunk

    def _set_mnt_cap(self, cap):
        # cap_max_new_tokens rung: requests admitted while engaged are
        # clamped to `cap` generated tokens (reshaped, not shed). None
        # restores uncapped admission.
        self._mnt_cap = None if cap is None else max(1, int(cap))

    def _dispatch(self):
        d = self._dev
        variant = d["variant"]
        spec = variant.endswith(".spec")
        sampled = variant.startswith("sampled")
        quant = self.pool.fmt.quantized
        # the compiled program closes over K (decode_steps) and D
        # (draft_depth) at make time, so the cache key carries them:
        # a brownout transition swaps programs without clearing the
        # cache, and recovery swaps straight back to the warm base one
        jit_key = (variant, self.decode_steps,
                   self.draft_depth if spec else 0)
        fn = self._decode_jit.get(jit_key)
        cold = fn is None or fn._compiled is None
        if fn is None:
            # decode keeps donation (the KV pools must not double-buffer),
            # so the pipeline runs but the artifact store is bypassed
            # (pir reports cache="bypass:donate")
            from ..pir import pir_jit
            name = ("serving.decode" + (".sampled" if sampled else "")
                    + (".spec" if spec else ""))
            maker = self._make_decode_spec if spec else self._make_decode
            fn = pir_jit(maker(sampled), name=name,
                         donate_argnums=(4, 5, 6, 7) if quant else (4, 5),
                         extra_key=({"lora": self.adapters.program_key}
                                    if self.adapters is not None else None))
            self._decode_jit[jit_key] = fn
            # program construction counts as a retrace: the hot-swap
            # contract pins this counter's delta to 0 across adapter churn
            _metric("jit_retrace_total").inc()
        args = [self.stacked, self.embed_w, self.norm_w, self._out_w,
                self.pool.k, self.pool.v]
        if quant:
            args += [self.pool.k_scale, self.pool.v_scale]
        args += [d["toks"], d["lens"], d["alive"], d["rem"], d["eos"],
                 d["tables"]]
        if spec:
            args.append(d["hist"])
        if sampled:
            args += [d["seeds"], d["do_sample"], d["temp"], d["top_k"],
                     d["top_p"]]
        if self.adapters is not None:
            # adapter pool + per-lane slot ids ride at the very END so
            # the donated KV-pool argnums above never shift
            ad = self.adapters
            args += [ad.A_q, ad.B_q, ad.A_v, ad.B_v, d["adapter_ids"]]
        out = fn(*args)
        if spec:
            (tile, counts, d["toks"], d["lens"], d["alive"], d["rem"],
             d["hist"]) = out[:7]
            rest = out[7:]
            tile = (tile, counts)
        else:
            tile, d["toks"], d["lens"], d["alive"], d["rem"] = out[:5]
            rest = out[5:]
        if quant:
            (self.pool.k, self.pool.v,
             self.pool.k_scale, self.pool.v_scale) = rest
        else:
            self.pool.k, self.pool.v = rest
        key = ("decode" + (".sampled" if sampled else "")
               + (".spec" if spec else ""))
        if self.compile_reports.get(key) is None:
            rep = getattr(fn, "report", None)
            self.compile_reports[key] = rep
            if rep is not None and rep.fallback == "verify":
                # the IR verifier statically rejected the decode program
                # (donation-alias or a structural rule): the engine keeps
                # serving on plain jax.jit, but donation safety of the
                # pool buffers is no longer *proven* — loud, not silent
                warnings.warn(
                    f"decode program {key!r} was rejected by the PIR "
                    f"verifier and fell back to plain jax.jit; see "
                    f"pir_verify_failures_total{{rule}} for the rule",
                    RuntimeWarning, stacklevel=2)
        self._phases.mark("compile" if cold else "decode.dispatch")
        return tile

    def _drain_all(self):
        while self._inflight:
            if not self._drain_one():
                return False
        return True

    def _drain_one(self):
        """Read back the oldest in-flight tile and run host bookkeeping.
        Returns False on a transient host-sync fault (tile kept, retried
        next step)."""
        infl = self._inflight[0]
        try:
            fault_point("serve.hostsync_read")
            t0 = time.perf_counter()
            self._phases.mark("decode.readback")
            if infl.spec:
                arr = (np.asarray(infl.tile[0]), np.asarray(infl.tile[1]))
            else:
                arr = np.asarray(infl.tile)
        except MemoryError:
            self._inflight.popleft()
            self._shed(self._decode_active())
            return True
        except _TRANSIENT_ERRORS:
            self._m_hostsync_retries.inc()
            return False
        except Exception as e:  # noqa: BLE001 — XLA OOM is backend-typed
            if "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e):
                self._inflight.popleft()
                self._shed(self._decode_active())
                return True
            raise
        t1 = time.perf_counter()
        self._inflight.popleft()
        self._m_hostsync.observe(t1 - t0)
        self._phases.mark("hostsync")
        self._cost_observe(infl.key, t1 - infl.t_dispatch)
        # one fused dispatch advances every active lane K tokens, so the
        # dispatch->readback wall time over K IS the per-token latency.
        # Exemplar: the first live lane's trace id stands for the tile
        # (one tile serves many lanes; the span links carry all of them)
        ex = None
        if self._reg.enabled:
            for r in infl.reqs:
                if r is not None and not r.done:
                    ex = r.trace_id
                    break
        if not infl.spec:
            per_tok = (t1 - infl.t_dispatch) / infl.k
            self._m_tpot.observe(per_tok, exemplar=ex)
            if self.scheduler is not None:
                self.scheduler.note_tpot(per_tok)
            for t in sorted({r.tenant for r in infl.reqs
                             if r is not None and not r.done}):
                _metric("serving_tenant_tpot_seconds",
                        tenant=t).observe(per_tok)
            if self.adapters is not None:
                for a in sorted({(r.adapter or "base") for r in infl.reqs
                                 if r is not None and not r.done}):
                    _metric("serving_adapter_tpot_seconds",
                            adapter=a).observe(per_tok)
        if self._rec.enabled:
            self._rec.record("readback", tile=infl.tile_id,
                             wait_ms=round((t1 - t0) * 1e3, 3))
        if self._tracer.enabled:
            self._trace_tile(infl, t1)
        if infl.spec:
            self._process_tile_spec(arr[0], arr[1], infl, t1, ex)
        else:
            self._process_tile(arr, infl)
        ph = self._phases
        if ph.enabled:
            # token crediting/emission since the hostsync mark is the
            # commit phase; the tile's device time splits evenly across
            # the tenants it served (one dispatch advances all lanes)
            ph.mark("commit")
            tenants = sorted({r.tenant for r in infl.reqs if r is not None})
            ph.credit_tenants(tenants, t1 - infl.t_dispatch)
        return True

    def _trace_tile(self, infl, t1):
        """Span-link a drained tile: one engine-side serving.decode_tile
        span linking every request it advanced, plus a request-side
        request.decode.tile span in each lane's trace group (the lanes
        here match _process_tile's crediting rules exactly)."""
        t0_ns = int(infl.t_dispatch * 1e9)
        dur_ns = int((t1 - infl.t_dispatch) * 1e9)
        links = []
        for lane, req in enumerate(infl.reqs):
            if (req is None or req.done
                    or self.lanes[lane] is not req
                    or self._lane_epoch[lane] != infl.epochs[lane]):
                continue
            links.append(req.trace_id)
            self._tracer.add_span(
                "request.decode.tile", t0_ns, dur_ns,
                trace_id=req.trace_id, tid=LANE_TID_BASE + lane,
                tid_name=f"lane {lane}",
                args={"rid": req.rid, "tile": infl.tile_id, "k": infl.k})
        self._tracer.add_span(
            "serving.decode_tile", t0_ns, dur_ns,
            args={"tile": infl.tile_id, "k": infl.k},
            links=links or None)

    # --- static cost model (pir/analysis.py CostModel) --------------------
    def _cost_observe(self, key, dt):
        """Predicted-vs-measured cost of one dispatch of the program
        compile_reports[key]. The FIRST measured dispatch calibrates the
        platform scale (its ratio is 1.0 by construction); every later
        one updates the per-program ratio gauge and the pooled error
        histogram whose exemplar carries the worst-predicted program."""
        rep = self.compile_reports.get(key)
        cost = getattr(rep, "cost", None)
        if cost is None or cost.raw_seconds <= 0 or dt <= 0:
            return
        if self._cost_scale is None:
            self._cost_scale = dt / cost.raw_seconds
        ratio = dt / (cost.raw_seconds * self._cost_scale)
        _metric("pir_cost_ratio", program=key).set(ratio)
        self._m_cost_err.observe(ratio, exemplar=key)

    def predicted_costs(self):
        """{program key: {flops, bytes, raw_seconds, seconds}} for every
        compiled program with a stamped ProgramCost; `seconds` is the
        calibrated prediction (None until a dispatch calibrated the
        scale). The loadgen harness derives its slo_headroom capacity
        signal from this."""
        out = {}
        for key, rep in self.compile_reports.items():
            cost = getattr(rep, "cost", None)
            if cost is None:
                continue
            out[key] = {"flops": cost.flops, "bytes": cost.bytes,
                        "raw_seconds": cost.raw_seconds,
                        "seconds": (cost.raw_seconds * self._cost_scale
                                    if self._cost_scale else None)}
        return out

    def predicted_service_seconds(self, output_tokens=32):
        """Calibrated engine seconds one request of `output_tokens`
        consumes: its share of the fused decode dispatches (a tile
        advances all max_batch lanes together) plus one prefill chunk.
        None until the cost model is calibrated — callers fall back to
        measured throughput."""
        if self._cost_scale is None:
            return None
        costs = self.predicted_costs()
        decode = next((c for k, c in sorted(costs.items())
                       if k.startswith("decode")), None)
        if decode is None or decode["seconds"] is None:
            return None
        # priced against the BASE decode program (the calibrated report
        # belongs to it): the estimate stays a stable capacity signal
        # for the undegraded engine even while the brownout ladder has
        # decode_steps temporarily shrunk
        t = (output_tokens / self._base_decode_steps) \
            * decode["seconds"] / self.max_batch
        prefill = next((c for k, c in sorted(costs.items())
                        if k.startswith("prefill")), None)
        if prefill is not None and prefill["seconds"] is not None:
            t += prefill["seconds"]
        return t

    def _process_tile(self, tile, infl):
        """Credit a [B, K] token tile: walk each lane's K tokens with the
        SAME eos/length rules the device applied, so host mirrors and
        device carry stay in lockstep without reading lens/alive back."""
        credited = 0
        for lane in range(self.max_batch):
            req = infl.reqs[lane]
            if (req is None or req.done
                    or self.lanes[lane] is not req
                    or self._lane_epoch[lane] != infl.epochs[lane]):
                continue            # occupancy changed while in flight
            for k in range(infl.k):
                self.lane_len[lane] += 1
                tok = int(tile[lane, k])
                self.lane_tok[lane] = tok
                credited += 1
                self._emit(lane, tok)
                if req.done or self.lanes[lane] is not req:
                    break
        self._m_tok_disp.set(credited)

    def _process_tile_spec(self, tile, counts, infl, t1, ex):
        """Credit a speculative tile: tokens [B, K, D+1] + counts [B, K].
        Row k of a lane commits its first counts[lane, k] tokens (the
        accepted draft run plus one correction token); counts drops to 0
        the step after the lane died on device. The host walk applies
        the same eos/length rules as the device, and the draft/accept
        accounting plus the acceptance-rate exemplar (worst-accepting
        request in the tile) are credited here, once per drained tile."""
        D = tile.shape[2] - 1
        credited = 0
        lanes_credited = 0
        drafted = accepted = 0
        worst = None
        for lane in range(self.max_batch):
            req = infl.reqs[lane]
            if (req is None or req.done
                    or self.lanes[lane] is not req
                    or self._lane_epoch[lane] != infl.epochs[lane]):
                continue            # occupancy changed while in flight
            lanes_credited += 1
            lane_drafted = lane_accepted = 0
            for k in range(infl.k):
                c = int(counts[lane, k])
                if c <= 0:
                    break
                lane_drafted += D
                lane_accepted += c - 1
                for i in range(c):
                    self.lane_len[lane] += 1
                    tok = int(tile[lane, k, i])
                    self.lane_tok[lane] = tok
                    credited += 1
                    self._emit(lane, tok)
                    if req.done or self.lanes[lane] is not req:
                        break
                if req.done or self.lanes[lane] is not req:
                    break
            drafted += lane_drafted
            accepted += lane_accepted
            if lane_drafted:
                rate = lane_accepted / lane_drafted
                if worst is None or rate < worst[0]:
                    worst = (rate, req.trace_id)
        if drafted:
            self._m_draft.inc(drafted)
            self._m_accept.inc(accepted)
            self._m_accept_rate.observe(
                accepted / drafted, exemplar=worst[1] if worst else None)
        self._m_tok_disp.set(credited)
        # effective per-token latency: the dispatch->readback wall over
        # the tokens one lane actually committed (> K with acceptance)
        eff = credited / max(1, lanes_credited)
        per_tok = (t1 - infl.t_dispatch) / max(1.0, eff)
        self._m_tpot.observe(per_tok, exemplar=ex)
        if self.scheduler is not None:
            self.scheduler.note_tpot(per_tok)
        for t in sorted({r.tenant for r in infl.reqs
                         if r is not None and not r.done}):
            _metric("serving_tenant_tpot_seconds", tenant=t).observe(per_tok)
        if self.adapters is not None:
            for a in sorted({(r.adapter or "base") for r in infl.reqs
                             if r is not None and not r.done}):
                _metric("serving_adapter_tpot_seconds",
                        adapter=a).observe(per_tok)

    # --- device-resident lane state ---------------------------------------
    def _upload_lane_state(self, active):
        """Rebuild the device lane state from the host mirrors — called
        ONLY on membership change (admission / retire / shed / recovery),
        never in the steady state. Counted so the A/B evidence can show
        uploads << dispatches."""
        B, MB = self.max_batch, self.max_blocks_per_seq
        tables = np.full((B, MB), self.pool.scratch_block, np.int32)
        lens = np.zeros(B, np.int32)
        toks = np.zeros(B, np.int32)
        alive = np.zeros(B, bool)
        rem = np.zeros(B, np.int32)
        eos = np.full(B, -1, np.int32)
        sampled = any(self.lanes[i].do_sample for i in active)
        if sampled:
            seeds = np.zeros(B, np.uint32)
            do_s = np.zeros(B, bool)
            temp = np.ones(B, np.float32)
            top_k = np.zeros(B, np.int32)
            top_p = np.ones(B, np.float32)
        for i in active:
            r = self.lanes[i]
            t = self.pool.tables[r.rid]
            tables[i, :len(t)] = t
            lens[i] = self.lane_len[i]
            toks[i] = self.lane_tok[i]
            alive[i] = True
            rem[i] = r.max_new_tokens - len(r.generated)
            if r.eos_token_id is not None:
                eos[i] = r.eos_token_id
            if sampled and r.do_sample:
                do_s[i] = True
                seeds[i] = r.sample_seed
                temp[i] = max(r.temperature, 1e-6)
                top_k[i] = r.top_k
                top_p[i] = r.top_p
        variant = ("sampled" if sampled else "greedy") + \
            (".spec" if self.spec else "")
        dev = dict(variant=variant,
                   toks=jnp.asarray(toks), lens=jnp.asarray(lens),
                   alive=jnp.asarray(alive), rem=jnp.asarray(rem),
                   eos=jnp.asarray(eos), tables=jnp.asarray(tables))
        if self.spec:
            # device-resident token history per lane (prompt + committed
            # tokens up to the cached length) — the drafter's lookup
            # corpus; extended ON DEVICE inside the scan, so like the
            # rest of the lane state it is only rebuilt here on
            # membership change
            hmax = self.max_blocks_per_seq * self.pool.block_size
            hist = np.zeros((B, hmax), np.int32)
            for i in active:
                r = self.lanes[i]
                seq = (np.concatenate([r.prompt,
                                       np.asarray(r.generated[:-1],
                                                  np.int32)])
                       if r.generated else r.prompt)
                n = min(seq.size, hmax)
                hist[i, :n] = seq[:n]
            dev["hist"] = jnp.asarray(hist)
        if sampled:
            dev.update(seeds=jnp.asarray(seeds), do_sample=jnp.asarray(do_s),
                       temp=jnp.asarray(temp), top_k=jnp.asarray(top_k),
                       top_p=jnp.asarray(top_p))
        if self.adapters is not None:
            # per-lane adapter slot ids: slot 0 (the reserved all-zero
            # adapter) for empty lanes and base-weight requests, so the
            # gathered low-rank delta is exactly 0 there
            aids = np.zeros(B, np.int32)
            for i in active:
                aids[i] = self.lanes[i].adapter_id
            dev["adapter_ids"] = jnp.asarray(aids)
        self._dev = dev
        self._dirty = False
        self._m_uploads.inc()
        if self._rec.enabled:
            self._rec.record("membership", active=list(active),
                             variant=dev["variant"])

    # --- compiled programs ------------------------------------------------
    def _make_prefill_chunk(self):
        cfg = self.cfg
        fmt = self.pool.fmt
        quant = fmt.quantized
        lora = self.adapters is not None

        def run(stacked, embed_w, norm_w, head_w, kpool, vpool, *rest):
            rest = list(rest)
            if lora:
                # adapter pools ride at the END of the arg list (after
                # every positional the storeless program takes) so the
                # two programs share their leading signature
                aq_p, bq_p, av_p, bv_p, aid = rest[-5:]
                rest = rest[:-5]
            if quant:
                kspool, vspool, ids, start, last_idx, table_row = rest
            else:
                ids, start, last_idx, table_row = rest
            h = jnp.take(embed_w, ids, axis=0)       # (1, C, H)

            def layer(hh, xs):
                if lora:
                    aq_l, bq_l, av_l, bv_l = xs[-4:]
                    xs = xs[:-4]
                    # single lane per prefill call: one scalar adapter id
                    # gathers this layer's (A, B) factors from the pool
                    delta = (aq_l[aid], bq_l[aid], av_l[aid], bv_l[aid])
                else:
                    delta = None
                if quant:
                    lp, kc, vc, ks, vs = xs
                    hh, pools = _llama_layer_prefill_chunk(
                        lp, hh, kc, vc, table_row, start, cfg,
                        fmt=fmt, kc_scale=ks, vc_scale=vs, lora=delta)
                else:
                    lp, kc, vc = xs
                    hh, pools = _llama_layer_prefill_chunk(
                        lp, hh, kc, vc, table_row, start, cfg, lora=delta)
                return hh, pools

            xs = ((stacked, kpool, vpool, kspool, vspool) if quant
                  else (stacked, kpool, vpool))
            if lora:
                xs = xs + (aq_p, bq_p, av_p, bv_p)
            h, pools = jax.lax.scan(layer, h, xs)
            h_last = h[0, last_idx]     # dynamic index: traced position
            logits = (_rms(h_last, norm_w, cfg["eps"]) @ head_w).astype(
                jnp.float32)
            return (logits,) + tuple(pools)

        return run

    def _make_decode(self, sampled: bool):
        cfg = self.cfg
        K = self.decode_steps
        scratch = self.pool.scratch_block
        fmt = self.pool.fmt
        quant = fmt.quantized
        lora = self.adapters is not None

        def run(stacked, embed_w, norm_w, head_w, kpool, vpool, *rest):
            rest = list(rest)
            if lora:
                # adapter pools + per-lane slot ids ride at the very END
                # (after sampling state) so the donated KV argnums and
                # the storeless signature prefix never shift
                aq_p, bq_p, av_p, bv_p, aids = rest[-5:]
                rest = rest[:-5]
            if quant:
                (kspool, vspool, toks, lens, alive, rem, eos_ids, tables,
                 *sample_state) = rest
            else:
                toks, lens, alive, rem, eos_ids, tables, *sample_state = \
                    rest
                kspool = vspool = None
            eps, theta = cfg["eps"], cfg["theta"]
            nh, nkv, hd = cfg["heads"], cfg["kv_heads"], cfg["head_dim"]
            B = toks.shape[0]
            if sampled:
                seeds, do_sample, temp, top_k, top_p = sample_state

            def step(carry, _):
                if quant:
                    (toks, lens, alive, rem, kpool, vpool,
                     kspool, vspool) = carry
                else:
                    toks, lens, alive, rem, kpool, vpool = carry
                    kspool = vspool = None
                h = jnp.take(embed_w, toks[:, None], axis=0)  # (B, 1, H)
                pos = lens[:, None]                            # write pos

                def layer(hh, xs):
                    if lora:
                        aq_l, bq_l, av_l, bv_l = xs[-4:]
                        xs = xs[:-4]
                    if quant:
                        lp, kc, vc, ks, vs = xs
                    else:
                        lp, kc, vc = xs
                        ks = vs = None
                    x = _rms(hh, lp["input_layernorm.weight"], eps)
                    q_lin = x @ lp["self_attn.q_proj.weight"]
                    v_lin = x @ lp["self_attn.v_proj.weight"]
                    if lora:
                        # per-lane batched low-rank delta: gather each
                        # lane's (A, B) factors by slot id, one einsum
                        # over the whole tile. Slot 0 is all-zeros, so
                        # base lanes add exactly 0.
                        aq = jnp.take(aq_l, aids, axis=0)   # (B, H, r)
                        bq = jnp.take(bq_l, aids, axis=0)   # (B, r, Dq)
                        q_lin = q_lin + jnp.einsum(
                            "bch,bhr,brd->bcd", x,
                            aq.astype(x.dtype), bq.astype(x.dtype))
                        av = jnp.take(av_l, aids, axis=0)
                        bv = jnp.take(bv_l, aids, axis=0)
                        v_lin = v_lin + jnp.einsum(
                            "bch,bhr,brd->bcd", x,
                            av.astype(x.dtype), bv.astype(x.dtype))
                    q = q_lin.reshape(B, 1, nh, hd)
                    k = (x @ lp["self_attn.k_proj.weight"]
                         ).reshape(B, 1, nkv, hd)
                    v = v_lin.reshape(B, 1, nkv, hd)
                    q = _rope(q, pos, theta)[:, 0]
                    k = _rope(k, pos, theta)[:, 0]
                    v = v[:, 0]
                    # passthrough formats route through write_to_cache
                    # with the exact pre-round-11 ops (byte-identical
                    # trace); quantized formats also update the scales
                    kc, vc, ks, vs = kv_write_token(
                        fmt if quant else None, kc, vc, ks, vs, k, v,
                        tables, lens, active=alive, scratch_block=scratch)
                    attn = paged_attention_decode_inner(
                        q, kc, vc, tables, lens + 1,
                        scale=1.0 / (hd ** 0.5),
                        fmt=fmt if quant else None,
                        k_scale_cache=ks, v_scale_cache=vs)
                    hh = hh + (attn.reshape(B, 1, nh * hd)
                               @ lp["self_attn.o_proj.weight"])
                    x = _rms(hh, lp["post_attention_layernorm.weight"],
                             eps)
                    gate = x @ lp["mlp.gate_proj.weight"]
                    up = x @ lp["mlp.up_proj.weight"]
                    hh = hh + ((jax.nn.silu(gate) * up)
                               @ lp["mlp.down_proj.weight"])
                    return hh, ((kc, vc, ks, vs) if quant else (kc, vc))

                xs = ((stacked, kpool, vpool, kspool, vspool) if quant
                      else (stacked, kpool, vpool))
                if lora:
                    xs = xs + (aq_p, bq_p, av_p, bv_p)
                h, pools = jax.lax.scan(layer, h, xs)
                if quant:
                    kpool, vpool, kspool, vspool = pools
                else:
                    kpool, vpool = pools
                logits = (_rms(h[:, 0], norm_w, eps) @ head_w).astype(
                    jnp.float32)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                if sampled:
                    samp = _device_sample(logits, seeds, lens, temp,
                                          top_k, top_p)
                    nxt = jnp.where(do_sample, samp, nxt)
                # frozen lanes re-emit their last token (never credited:
                # the host walk stops at the same eos/length boundary)
                nxt = jnp.where(alive, nxt, toks)
                rem = rem - alive.astype(rem.dtype)
                alive_next = alive & (nxt != eos_ids) & (rem > 0)
                lens = lens + alive.astype(lens.dtype)
                out = (nxt, lens, alive_next, rem, kpool, vpool)
                if quant:
                    out = out + (kspool, vspool)
                return out, nxt

            carry0 = (toks, lens, alive, rem, kpool, vpool)
            if quant:
                carry0 = carry0 + (kspool, vspool)
            carry, tile = jax.lax.scan(step, carry0, None, length=K)
            toks, lens, alive, rem = carry[:4]
            return (jnp.moveaxis(tile, 0, 1), toks, lens, alive, rem
                    ) + tuple(carry[4:])

        return run

    def _make_decode_spec(self, sampled: bool):
        """The speculative fused decode program: each of the K scan steps
        proposes draft_depth tokens from the drafter, verifies the step
        token + drafts in ONE batched forward (C = draft_depth+1 queries
        per lane against the paged pool), accepts the leading run of
        drafts that match what the sequential policy would emit, rolls
        back the rejected slots' cache writes, and commits the accepted
        run plus one correction token — up to K*(draft_depth+1) tokens
        per dispatch, with the committed stream exactly equal to the
        non-speculative path (greedy by argmax equality; sampled lanes
        by the position-keyed PRNG, which makes the sequential sample at
        every position a pure function of (seed, position))."""
        cfg = self.cfg
        K = self.decode_steps
        D = self.draft_depth
        C = D + 1
        scratch = self.pool.scratch_block
        fmt = self.pool.fmt
        quant = fmt.quantized
        hmax = self.max_blocks_per_seq * self.pool.block_size
        drafter = self._drafter
        ngram = self.draft_ngram
        lora = self.adapters is not None

        def run(stacked, embed_w, norm_w, head_w, kpool, vpool, *rest):
            rest = list(rest)
            if lora:
                # same tail contract as the base decode program: adapter
                # state last, donated argnums untouched
                aq_p, bq_p, av_p, bv_p, aids = rest[-5:]
                rest = rest[:-5]
            if quant:
                (kspool, vspool, toks, lens, alive, rem, eos_ids, tables,
                 hist, *sample_state) = rest
            else:
                (toks, lens, alive, rem, eos_ids, tables, hist,
                 *sample_state) = rest
                kspool = vspool = None
            eps, theta = cfg["eps"], cfg["theta"]
            nh, nkv, hd = cfg["heads"], cfg["kv_heads"], cfg["head_dim"]
            B = toks.shape[0]
            rows = jnp.arange(B)
            if sampled:
                seeds, do_sample, temp, top_k, top_p = sample_state

            def step(carry, _):
                if quant:
                    (toks, lens, alive, rem, hist, kpool, vpool,
                     kspool, vspool) = carry
                else:
                    toks, lens, alive, rem, hist, kpool, vpool = carry
                    kspool = vspool = None
                # record the step token into the running history (dead
                # lanes scatter out of bounds, which JAX drops)
                hidx = jnp.where(alive, lens, hmax)
                hist = hist.at[rows, hidx].set(toks)
                if drafter is not None:
                    drafts = drafter(hist, lens, toks, D).astype(jnp.int32)
                else:
                    drafts = _ngram_draft(hist, lens, toks, D, ngram)
                u = jnp.concatenate([toks[:, None], drafts], axis=1)
                didx = jnp.where(alive[:, None],
                                 lens[:, None] + 1 + jnp.arange(D)[None, :],
                                 hmax)
                hist = hist.at[rows[:, None], didx].set(drafts)
                h = jnp.take(embed_w, u, axis=0)               # (B, C, H)
                pos = lens[:, None] + jnp.arange(C)[None, :]   # (B, C)

                def layer(hh, xs):
                    if lora:
                        aq_l, bq_l, av_l, bv_l = xs[-4:]
                        xs = xs[:-4]
                    if quant:
                        lp, kc, vc, ks, vs = xs
                    else:
                        lp, kc, vc = xs
                        ks = vs = None
                    x = _rms(hh, lp["input_layernorm.weight"], eps)
                    q_lin = x @ lp["self_attn.q_proj.weight"]
                    v_lin = x @ lp["self_attn.v_proj.weight"]
                    if lora:
                        # x is (B, C, H) here — the same batched einsum
                        # covers all C verify positions of every lane
                        aq = jnp.take(aq_l, aids, axis=0)
                        bq = jnp.take(bq_l, aids, axis=0)
                        q_lin = q_lin + jnp.einsum(
                            "bch,bhr,brd->bcd", x,
                            aq.astype(x.dtype), bq.astype(x.dtype))
                        av = jnp.take(av_l, aids, axis=0)
                        bv = jnp.take(bv_l, aids, axis=0)
                        v_lin = v_lin + jnp.einsum(
                            "bch,bhr,brd->bcd", x,
                            av.astype(x.dtype), bv.astype(x.dtype))
                    q = q_lin.reshape(B, C, nh, hd)
                    k = (x @ lp["self_attn.k_proj.weight"]
                         ).reshape(B, C, nkv, hd)
                    v = v_lin.reshape(B, C, nkv, hd)
                    q = _rope(q, pos, theta)
                    k = _rope(k, pos, theta)
                    # kv.write effect scope (stamped inside the callee):
                    # the verify-write must stay ordered before the
                    # rollback below — the PIR effect-order rule rejects
                    # any pass that migrates one past the other
                    kc, vc, ks, vs, saved = kv_write_tokens(
                        fmt if quant else None, kc, vc, ks, vs, k, v,
                        tables, lens, active=alive, scratch_block=scratch)
                    attn = paged_attention_verify(
                        q, kc, vc, tables, lens, scale=1.0 / (hd ** 0.5),
                        fmt=fmt if quant else None,
                        k_scale_cache=ks, v_scale_cache=vs)
                    hh = hh + (attn.reshape(B, C, nh * hd)
                               @ lp["self_attn.o_proj.weight"])
                    x = _rms(hh, lp["post_attention_layernorm.weight"],
                             eps)
                    gate = x @ lp["mlp.gate_proj.weight"]
                    up = x @ lp["mlp.up_proj.weight"]
                    hh = hh + ((jax.nn.silu(gate) * up)
                               @ lp["mlp.down_proj.weight"])
                    out = (kc, vc, ks, vs) if quant else (kc, vc)
                    return hh, (out, saved)

                xs = ((stacked, kpool, vpool, kspool, vspool) if quant
                      else (stacked, kpool, vpool))
                if lora:
                    xs = xs + (aq_p, bq_p, av_p, bv_p)
                h, (pools, saved) = jax.lax.scan(layer, h, xs)
                logits = (_rms(h, norm_w, eps) @ head_w).astype(
                    jnp.float32)                               # (B, C, V)
                # g[:, i] is the token the sequential policy emits at
                # position lens+i+1 GIVEN the drafts up to i were right —
                # so the committed tokens are exactly a prefix of g
                g = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                if sampled:
                    samp = jnp.stack(
                        [_device_sample(logits[:, i], seeds, lens + i,
                                        temp, top_k, top_p)
                         for i in range(C)], axis=1)
                    g = jnp.where(do_sample[:, None], samp, g)
                # leading-run acceptance; +1 = the correction token
                matches = (drafts == g[:, :D]).astype(jnp.int32)
                n_acc = jnp.cumprod(matches, axis=1).sum(axis=1)
                commits = jnp.minimum(n_acc + 1, rem)
                iseos = g == eos_ids[:, None]
                eos_clip = jnp.where(iseos.any(axis=1),
                                     jnp.argmax(iseos, axis=1) + 1, C)
                commits = jnp.minimum(commits, eos_clip)
                commits = jnp.where(alive, commits, 0)
                # roll back the rejected slots' writes layer by layer
                # (kept and dead-lane restores are routed to scratch)
                keep = ((jnp.arange(C)[None, :] < commits[:, None])
                        & alive[:, None])

                def restore(_, xs):
                    if quant:
                        (kc, vc, ks, vs), sv = xs
                    else:
                        (kc, vc), sv = xs
                        ks = vs = None
                    kc, vc, ks, vs = kv_rollback_tokens(
                        fmt if quant else None, kc, vc, ks, vs, sv,
                        tables, lens, keep, active=alive,
                        scratch_block=scratch)
                    return None, ((kc, vc, ks, vs) if quant
                                  else (kc, vc))

                _, pools = jax.lax.scan(restore, None, (pools, saved))
                if quant:
                    kpool, vpool, kspool, vspool = pools
                else:
                    kpool, vpool = pools
                last = jnp.clip(commits - 1, 0, C - 1)
                g_last = g[rows, last]
                toks_next = jnp.where(alive, g_last, toks)
                ended_eos = alive & (commits > 0) & (g_last == eos_ids)
                rem = rem - commits
                alive_next = alive & ~ended_eos & (rem > 0)
                lens = lens + commits
                out = (toks_next, lens, alive_next, rem, hist,
                       kpool, vpool)
                if quant:
                    out = out + (kspool, vspool)
                return out, (g, commits.astype(jnp.int32))

            carry0 = (toks, lens, alive, rem, hist, kpool, vpool)
            if quant:
                carry0 = carry0 + (kspool, vspool)
            carry, (tile, counts) = jax.lax.scan(step, carry0, None,
                                                 length=K)
            toks, lens, alive, rem, hist = carry[:5]
            return (jnp.moveaxis(tile, 0, 1), jnp.moveaxis(counts, 0, 1),
                    toks, lens, alive, rem, hist) + tuple(carry[5:])

        return run


def _ngram_draft(hist, lens, toks, depth, ngram):
    """Default self-drafter: prompt-lookup decoding. For each lane, find
    the most recent earlier occurrence of the trailing `ngram`-token
    suffix of (history + step token) and propose the `depth` tokens that
    followed it; lanes with no match propose `depth` copies of the step
    token (a valid — if rarely accepted — draft). Pure jnp over the
    device-resident history buffer, so it traces into the fused scan."""
    hmax = hist.shape[1]
    cand = jnp.arange(hmax)

    def one(h, n, t):
        # h[n] is the step token (scattered by the caller); compare the
        # ngram ending at each candidate position against the one at n.
        # Candidates must leave the whole continuation in the PAST
        # (cand + depth < n): a more recent match would read positions
        # >= n, which hold the previous step's rejected-draft leftovers
        ok = (cand >= ngram - 1) & (cand + depth < n)
        for gback in range(ngram):
            ok &= (h[jnp.clip(cand - gback, 0, hmax - 1)]
                   == h[jnp.clip(n - gback, 0, hmax - 1)])
        j = jnp.max(jnp.where(ok, cand, -1))
        cont = h[jnp.clip(j + 1 + jnp.arange(depth), 0, hmax - 1)]
        return jnp.where(j >= 0, cont, jnp.full((depth,), t))

    return jax.vmap(one)(hist, lens, toks).astype(jnp.int32)


def _device_sample(logits, seeds, lens, temperature, top_k, top_p):
    """Per-lane on-device sampling: temperature -> top-k -> nucleus ->
    categorical, all vectorized over lanes. Randomness comes from
    fold_in(key(lane_seed), absolute_position), so a lane's stream is a
    pure function of (seed, position) — byte-identical no matter how the
    decode steps are tiled (decode_steps=1 vs K)."""
    B, V = logits.shape
    z = logits / jnp.maximum(temperature, 1e-6)[:, None]
    svals = jnp.sort(z, axis=-1)[:, ::-1]               # descending
    idx = jnp.clip(top_k - 1, 0, V - 1)
    kth = jnp.take_along_axis(svals, idx[:, None], axis=-1)
    z = jnp.where((top_k > 0)[:, None] & (z < kth), -jnp.inf, z)
    probs = jax.nn.softmax(z, axis=-1)
    order = jnp.argsort(-probs, axis=-1)
    sp = jnp.take_along_axis(probs, order, axis=-1)
    cum = jnp.cumsum(sp, axis=-1)
    keep_sorted = (cum - sp) < top_p[:, None]
    keep_sorted = keep_sorted.at[:, 0].set(True)  # top_p=0 keeps argmax
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(B)[:, None], order].set(keep_sorted)
    z = jnp.where((top_p < 1.0)[:, None] & ~keep, -jnp.inf, z)
    keys = jax.vmap(
        lambda s, p: jax.random.fold_in(jax.random.key(s), p))(seeds, lens)
    return jax.vmap(jax.random.categorical)(keys, z).astype(jnp.int32)
