"""Deterministic open-loop traffic harness for the serving engine.

reference capability: the reference validates its serving stack with ad
hoc client scripts; load behaviour (tail latency under bursts, shed
onset, SLO compliance at a target rate) is folklore. Here traffic is a
SCENARIO: a named, seeded arrival process plus a distribution over
prompt/output lengths, tenants and sampling knobs. `build_schedule`
turns (scenario, seed) into an explicit arrival list — the same pair
always yields byte-identical arrivals, so a load test is replayable
evidence, not a weather report.

The runner is OPEN LOOP: arrivals are issued by the schedule clock, not
by completion of earlier requests, so overload actually overloads (a
closed loop self-throttles and hides saturation — the coordinated-
omission trap). Each clock tick passes the `serve.loadgen_tick` fault
site; an injected failure models clock skew / a stalled driver — the
tick is skipped and counted (`loadgen_ticks_skipped_total`) and its
arrivals are re-issued on the next tick, because issuance is "everything
scheduled at or before now", not "this tick's quantum".

While driving the engine the runner samples a timeline: goodput, shed
fraction, offered rate, and the capacity signal
``headroom = 1 - offered_rate x predicted_service_seconds`` from the
PIR cost model (pir/analysis.py CostModel, calibrated by the engine's
first measured dispatch). The `slo_headroom` / `serving_overload`
gauges therefore cross into alarm BEFORE goodput collapses — the
leading indicator the SLO engine's burn rate (a trailing indicator)
cannot provide. The run report carries per-scenario TTFT/TPOT quantiles
(histogram bucket deltas over the run window), finish reasons, the
phase accountant's attribution coverage, the predicted-vs-measured cost
ratios, and an `SLOEngine` verdict.
"""

from __future__ import annotations

import hashlib
import json
import random
import time

import numpy as np

from ..observability.catalog import metric as _metric
from ..observability.metrics import get_registry as _get_registry
from ..observability.metrics import snapshot as _snapshot
from ..observability.quantiles import quantiles_from_cumulative
from ..observability.autoscale import check_verdict as _check_autoscale
from ..observability.recorder import get_recorder as _get_recorder
from ..observability.slo import SLOEngine
from ..observability.timeseries import RECORDING_RULES, MetricsSampler
from ..profiler.phases import get_phase_accountant as _get_phases
from ..resilience.faults import fault_point
from .scheduler import PRIORITY_CLASSES
from .serving import BackpressureError

__all__ = ["Scenario", "SCENARIOS", "build_schedule", "run_scenario",
           "check_report", "REPORT_FORMAT", "KNOWN_FINISH_REASONS"]

REPORT_FORMAT = 2

# finish reasons that count as goodput (mirrors the availability SLO's
# good set in observability/slo.py DEFAULT_SLOS)
GOOD_REASONS = ("eos", "length")

# every reason a request may legally finish with (serving.py _finish);
# check_report flags anything outside this set — a request must never
# end in an unclassifiable state, scheduler or no scheduler
KNOWN_FINISH_REASONS = ("eos", "length", "timeout", "shed", "rejected")


class Scenario:
    """One named traffic shape: an arrival process (poisson rate, burst
    trains, or a linear ramp) over a distribution of prompt/output
    lengths, tenants (weighted), sampling knobs and deadlines. All
    randomness is drawn from one seeded stream in build_schedule — a
    Scenario itself is immutable configuration."""

    __slots__ = ("name", "arrival", "rate_rps", "duration_s",
                 "rate_end_rps", "burst_n", "burst_every_s",
                 "prompt_len", "output_tokens", "tenants", "priorities",
                 "do_sample", "temperature", "top_k", "top_p",
                 "deadline_s", "shared_prefix_len", "adapter_population",
                 "adapter_zipf", "description")

    def __init__(self, name, arrival="poisson", rate_rps=10.0,
                 duration_s=1.0, rate_end_rps=None, burst_n=4,
                 burst_every_s=0.25, prompt_len=(4, 16),
                 output_tokens=(4, 12), tenants=(("-", 1.0),),
                 priorities=(("interactive", 1.0),),
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 deadline_s=None, shared_prefix_len=0,
                 adapter_population=0, adapter_zipf=1.1, description=""):
        if arrival not in ("poisson", "burst", "ramp"):
            raise ValueError(f"unknown arrival process {arrival!r}")
        for p, _w in priorities:
            if p not in PRIORITY_CLASSES:
                raise ValueError(
                    f"unknown priority class {p!r}; registered: "
                    f"{list(PRIORITY_CLASSES)}")
        self.name = str(name)
        self.arrival = arrival
        self.rate_rps = float(rate_rps)
        self.duration_s = float(duration_s)
        self.rate_end_rps = (None if rate_end_rps is None
                             else float(rate_end_rps))
        self.burst_n = int(burst_n)
        self.burst_every_s = float(burst_every_s)
        self.prompt_len = (int(prompt_len[0]), int(prompt_len[1]))
        self.output_tokens = (int(output_tokens[0]), int(output_tokens[1]))
        self.tenants = tuple((str(t), float(w)) for t, w in tenants)
        self.priorities = tuple((str(p), float(w)) for p, w in priorities)
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        # round 18: tokens of tenant-common system prompt prepended to
        # every request's (per-request) tail — the prefix-cache workload
        self.shared_prefix_len = int(shared_prefix_len)
        # round 22: every arrival names one of adapter_population demo
        # LoRA adapters ("lora0".."loraN-1"), drawn Zipf(adapter_zipf)
        # so a hot head stays resident while the tail churns slots — the
        # multi-adapter hot-swap workload (0 = base-model traffic only)
        self.adapter_population = int(adapter_population)
        self.adapter_zipf = float(adapter_zipf)
        self.description = str(description)


# The scenario matrix. Sizes are tier-1-friendly (tens of requests on a
# tiny model); production sweeps scale rate_rps/duration_s via the
# run_scenario overrides without touching the distributions.
SCENARIOS = {
    "chat": Scenario(
        "chat", arrival="poisson", rate_rps=20.0, duration_s=1.5,
        prompt_len=(4, 24), output_tokens=(4, 12),
        tenants=(("acme", 3.0), ("zee", 1.0), ("-", 1.0)),
        deadline_s=10.0,
        description="interactive chat: short prompts, short replies, "
                    "Poisson arrivals, tight TTFT expectations"),
    "long_document": Scenario(
        "long_document", arrival="poisson", rate_rps=4.0, duration_s=1.5,
        prompt_len=(32, 96), output_tokens=(4, 8),
        tenants=(("lawfirm", 1.0), ("-", 1.0)), deadline_s=20.0,
        description="long-document QA: chunked-prefill-heavy prompts, "
                    "few output tokens"),
    "offline_batch": Scenario(
        "offline_batch", arrival="burst", rate_rps=16.0, duration_s=1.5,
        burst_n=8, burst_every_s=0.5, prompt_len=(8, 32),
        output_tokens=(8, 16), tenants=(("batch", 1.0),),
        priorities=(("batch", 1.0),),
        description="offline batch: burst trains (a queue worker "
                    "flushing), throughput over latency, no deadlines"),
    "structured_output": Scenario(
        "structured_output", arrival="ramp", rate_rps=2.0,
        rate_end_rps=24.0, duration_s=2.0, prompt_len=(6, 20),
        output_tokens=(4, 10), tenants=(("jsonsvc", 1.0),),
        priorities=(("interactive", 2.0), ("batch", 1.0)),
        do_sample=True, temperature=0.8, top_p=0.95, deadline_s=15.0,
        description="structured-output extraction: sampled decode, "
                    "mixed interactive/batch classes, arrival rate "
                    "ramping into saturation — the scheduler's chaos "
                    "probe"),
    "shared_prefix": Scenario(
        "shared_prefix", arrival="poisson", rate_rps=14.0, duration_s=1.5,
        prompt_len=(4, 12), output_tokens=(4, 10),
        tenants=(("acme", 2.0), ("zee", 1.0)), shared_prefix_len=32,
        deadline_s=15.0,
        description="tenant-common system prompt (32 shared tokens) + "
                    "short per-request tail: the cross-request prefix "
                    "cache workload — after one cold prefill per tenant "
                    "every admission should resolve the shared blocks "
                    "from the index and prefill only the tail"),
    "multi_adapter": Scenario(
        "multi_adapter", arrival="poisson", rate_rps=14.0, duration_s=1.5,
        prompt_len=(4, 14), output_tokens=(4, 10),
        tenants=(("acme", 2.0), ("zee", 1.0), ("-", 1.0)),
        adapter_population=6, adapter_zipf=1.1, deadline_s=15.0,
        description="per-tenant LoRA serving: every request names one "
                    "of 6 demo adapters (Zipf-skewed, population wider "
                    "than the slot pool) so hot heads stay resident "
                    "while the tail hot-loads and evicts through the "
                    "store — the recompile-free swap workload; the "
                    "report's swap_recompiles must stay 0"),
}


def _pick_weighted(rng, pairs):
    names = [t for t, _ in pairs]
    weights = [w for _, w in pairs]
    return rng.choices(names, weights=weights, k=1)[0]


def _arrival(scenario, rng, t):
    lo, hi = scenario.prompt_len
    olo, ohi = scenario.output_tokens
    a = {
        "t": round(float(t), 6),
        "prompt_len": rng.randint(lo, hi),
        "output_tokens": rng.randint(olo, ohi),
        "tenant": _pick_weighted(rng, scenario.tenants),
        "priority": _pick_weighted(rng, scenario.priorities),
        "prompt_seed": rng.randrange(1 << 30),
        "sample_seed": rng.randrange(1 << 30),
    }
    if scenario.adapter_population > 0:
        # Zipf over the population: weight 1/(rank+1)^s — adapter-less
        # scenarios draw nothing here, so their schedules (and digests)
        # are byte-identical to pre-round-22 runs
        n = scenario.adapter_population
        s = scenario.adapter_zipf
        weights = [1.0 / float(i + 1) ** s for i in range(n)]
        a["adapter"] = "lora%d" % rng.choices(range(n),
                                              weights=weights, k=1)[0]
    return a


def build_schedule(scenario, seed=0, rate_rps=None, duration_s=None):
    """(scenario, seed) -> ordered arrival list. Deterministic: one
    `random.Random(f"{name}:{seed}")` stream drives inter-arrival gaps,
    lengths, tenants and per-request seeds, so equal inputs produce an
    equal schedule (test-pinned). `rate_rps`/`duration_s` override the
    scenario's defaults (the overload-sweep knob)."""
    if isinstance(scenario, str):
        scenario = SCENARIOS[scenario]
    rng = random.Random(f"{scenario.name}:{int(seed)}")
    rate = float(rate_rps if rate_rps is not None else scenario.rate_rps)
    dur = float(duration_s if duration_s is not None
                else scenario.duration_s)
    out = []
    if scenario.arrival == "poisson":
        t = 0.0
        while True:
            t += rng.expovariate(rate)
            if t >= dur:
                break
            out.append(_arrival(scenario, rng, t))
    elif scenario.arrival == "burst":
        # burst trains: every burst_every_s a worker flushes burst_n
        # requests nearly at once (small jitter keeps ordering honest)
        t = 0.0
        while t < dur:
            for _ in range(scenario.burst_n):
                out.append(_arrival(scenario, rng,
                                    t + rng.uniform(0.0, 0.01)))
            t += scenario.burst_every_s
    else:   # ramp — Poisson thinning against the envelope rate
        r_end = (scenario.rate_end_rps if scenario.rate_end_rps is not None
                 else rate)
        r_max = max(rate, r_end)
        t = 0.0
        while True:
            t += rng.expovariate(r_max)
            if t >= dur:
                break
            r_t = rate + (r_end - rate) * (t / dur)
            if rng.random() < r_t / r_max:
                out.append(_arrival(scenario, rng, t))
    out.sort(key=lambda a: a["t"])
    return out


def schedule_digest(schedule):
    """Stable content hash of a schedule — the replay check two runs
    compare before trusting a latency diff."""
    blob = json.dumps(schedule, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _prompt_tokens(prompt_seed, length, vocab):
    """Deterministic pseudo-prompt: a Weyl sequence over the vocab
    (never token 0, so padding stays distinguishable)."""
    lo, span = 1, max(1, int(vocab) - 1)
    idx = np.arange(int(length), dtype=np.int64)
    return ((int(prompt_seed) + idx * 2654435761) % span + lo).astype(
        np.int32)


def _tenant_prefix(scenario_name, tenant, length, vocab):
    """Round 18: the tenant-common system prompt — a Weyl sequence whose
    seed is a stable content hash of (scenario, tenant), so every
    request of one tenant shares byte-identical leading tokens (what
    the engine's prefix index actually keys on) while tenants never
    collide with each other."""
    h = hashlib.sha256(f"{scenario_name}:{tenant}".encode()).digest()
    return _prompt_tokens(int.from_bytes(h[:4], "big"), length, vocab)


# -- snapshot helpers (the slo.py windowing idea, localized) ---------------

def _hist_cum(snapshot_doc, name):
    """Histogram family -> merged {le: cumulative count} across label
    children (per-tenant siblings roll up into the scenario view)."""
    merged = {}
    for m in snapshot_doc.get("metrics", []):
        if m.get("name") != name:
            continue
        for s in m.get("samples", []):
            for le, cum in s.get("buckets", []):
                key = ("+Inf" if (isinstance(le, str) or le == float("inf"))
                       else float(le))
                merged[key] = merged.get(key, 0) + int(cum)
    return merged


def _hist_delta(new, old):
    finite = sorted(k for k in new if k != "+Inf")
    buckets = [(le, max(0, new.get(le, 0) - old.get(le, 0)))
               for le in finite]
    buckets.append(("+Inf", max(0, new.get("+Inf", 0)
                                - old.get("+Inf", 0))))
    return buckets


def _quantile_block(snap0, snap1, name):
    buckets = _hist_delta(_hist_cum(snap1, name), _hist_cum(snap0, name))
    count = buckets[-1][1] if buckets else 0
    qs = quantiles_from_cumulative(buckets)
    return {"count": int(count),
            "p50": qs.get(0.5), "p95": qs.get(0.95), "p99": qs.get(0.99)}


def _gauge_samples(snapshot_doc, name):
    out = {}
    for m in snapshot_doc.get("metrics", []):
        if m.get("name") != name:
            continue
        for s in m.get("samples", []):
            labels = s.get("labels") or {}
            key = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            out[key or "-"] = float(s.get("value", 0.0))
    return out


def _counter_total(snapshot_doc, name):
    """Sum of a counter family across label children (0.0 when the
    metric never fired or the registry is disabled)."""
    total = 0.0
    for m in snapshot_doc.get("metrics", []):
        if m.get("name") != name:
            continue
        for s in m.get("samples", []):
            total += float(s.get("value", 0.0))
    return total


def _hist_cum_by(snapshot_doc, name, label):
    """Like _hist_cum, but keyed by one label's value instead of merged
    across children — the per-adapter latency view."""
    out = {}
    for m in snapshot_doc.get("metrics", []):
        if m.get("name") != name:
            continue
        for s in m.get("samples", []):
            lv = (s.get("labels") or {}).get(label)
            if lv is None:
                continue
            merged = out.setdefault(str(lv), {})
            for le, cum in s.get("buckets", []):
                key = ("+Inf" if (isinstance(le, str) or le == float("inf"))
                       else float(le))
                merged[key] = merged.get(key, 0) + int(cum)
    return out


# -- multi-adapter plumbing (round 22) -------------------------------------

def _engines_of(engine):
    """The concrete serving engines behind the harness handle: a plain
    engine is itself; a MeshRouter contributes every in-process replica
    engine (RPC proxies have no stacked params and are skipped — a
    process-worker mesh must arrive with stores pre-installed)."""
    if hasattr(engine, "mesh_report"):
        return [rep.engine for rep in engine.pool
                if hasattr(rep.engine, "stacked")]
    return [engine]


def _ensure_adapter_stores(engine, names):
    """Install the deterministic demo store on every store-less engine
    the scenario will touch. Only legal on a COLD engine: programs
    already compiled without the lora argument tail must never be fed
    an adapter-carrying dispatch."""
    from .adapters import demo_store_for_engine
    n_slots = max(2, len(names))    # one fewer usable slot than names,
    for eng in _engines_of(engine):  # so the Zipf tail actually evicts
        store = getattr(eng, "adapters", None)
        if store is not None:
            missing = [n for n in names if not store.can_serve(n)]
            if missing:
                raise ValueError(
                    f"engine's adapter store cannot serve {missing}; "
                    f"registered: {store.names()}")
            continue
        if eng._prefill_jit or eng._decode_jit:
            raise ValueError(
                "scenario names adapters but the engine is already warm "
                "and has no adapter store; build it with adapters=... "
                "(compiled programs lack the lora argument tail)")
        eng.adapters = demo_store_for_engine(eng, names, n_slots=n_slots)


def _warm_adapter_programs(engine, scenario, vocab):
    """Compile every program the run will need BEFORE the measurement
    window opens: one prefill per bucket width the scenario's prompts
    can reach, plus the decode program, each through an adapter-carrying
    request. The report's `swap_recompiles` is the jit_retrace_total
    delta over the run window — after this warmup any nonzero delta IS
    an adapter-churn recompile, which the hot-swap contract forbids."""
    max_prompt = scenario.prompt_len[1] + scenario.shared_prefix_len
    for eng in _engines_of(engine):
        store = getattr(eng, "adapters", None)
        warm_adapter = (store.names()[0]
                        if store is not None and store.names() else None)
        covering = [b for b in eng.buckets if b >= max_prompt]
        top = covering[0] if covering else eng.buckets[-1]
        for width in [b for b in eng.buckets if b <= top]:
            eng.add_request(
                _prompt_tokens(width, width, vocab), max_new_tokens=2,
                do_sample=scenario.do_sample,
                temperature=scenario.temperature, top_k=scenario.top_k,
                top_p=scenario.top_p, adapter=warm_adapter)
        while eng.has_work():
            eng.step()
        # warmup requests are scaffolding, not traffic: drop them so
        # finish-reason / tenant accounting sees only the schedule's
        eng.finished.clear()
        # warmup used every prefill program exactly once (cold, so
        # unmeasured) — the first MEASURED dispatch was the decode
        # tile whose readback wall still contained the compile. That
        # one-shot calibration would price decode ~100x too high and
        # pin slo_headroom (and a scheduler's brownout ladder) at the
        # floor for the whole run. Drop it; the run's first dispatch
        # re-calibrates against warm programs.
        eng._cost_scale = None


# -- the runner ------------------------------------------------------------

def run_scenario(engine, scenario, seed=0, rate_rps=None, duration_s=None,
                 max_wall_s=None, sample_every_s=0.2, slo_engine=None,
                 drain=True, sampler="auto"):
    """Drive `engine` with the scenario's schedule in real time; returns
    the run report (REPORT_FORMAT). Open loop: every tick issues all
    arrivals scheduled at or before now, then advances the engine one
    step. `drain` keeps stepping after the last arrival until the engine
    idles (False = stop at schedule end, for saturation sweeps where the
    backlog would never drain). `sampler` is the embedded TSDB hook:
    "auto" attaches a MetricsSampler ticked on the schedule clock only
    when the metrics registry is enabled (plane off = zero work), None
    disables it, or pass your own MetricsSampler."""
    if isinstance(scenario, str):
        scenario = SCENARIOS[scenario]
    schedule = build_schedule(scenario, seed, rate_rps=rate_rps,
                              duration_s=duration_s)
    dur = float(duration_s if duration_s is not None
                else scenario.duration_s)
    max_wall = float(max_wall_s) if max_wall_s is not None else dur + 30.0
    vocab = int(engine.embed_w.shape[0])
    mean_out = (sum(a["output_tokens"] for a in schedule)
                / max(1, len(schedule)))

    # round 22: multi-adapter runs — install the demo store on cold
    # store-less engines, then compile every program (adapter tail
    # included) BEFORE snap0 so the run window's jit_retrace_total
    # delta isolates adapter-churn recompiles (contract: zero)
    wants_adapters = scenario.adapter_population > 0
    adapter_names = sorted({a["adapter"] for a in schedule
                            if a.get("adapter")})
    if wants_adapters:
        _ensure_adapter_stores(engine, adapter_names)
        _warm_adapter_programs(engine, scenario, vocab)

    reg = _get_registry()
    phases = _get_phases()
    slo_eng = slo_engine if slo_engine is not None \
        else SLOEngine(window_s=max_wall + 60.0)
    snap0 = _snapshot(reg)
    if sampler == "auto":
        sampler = MetricsSampler() if reg.enabled else None
    t0 = time.perf_counter()
    slo_eng.observe(snap0, t0)
    if sampler is not None:
        sampler.sample(0.0)   # prime the rate/window state at run start

    m_arrivals = _metric("loadgen_arrivals_total", scenario=scenario.name)
    m_skipped = _metric("loadgen_ticks_skipped_total")
    m_headroom = _metric("slo_headroom")
    m_overload = _metric("serving_overload")

    idx = 0
    issued = 0
    rejected = 0
    ticks = 0
    ticks_skipped = 0
    offered_t = []      # schedule-clock time of every issue ATTEMPT
    timeline = []
    next_sample = 0.0
    headroom_floor = None

    def sample(now):
        nonlocal headroom_floor
        fin = engine.finished
        done = len(fin)
        good = sum(1 for r in fin.values()
                   if r.finish_reason in GOOD_REASONS)
        sheds = rejected + sum(1 for r in fin.values()
                               if r.finish_reason == "shed")
        attempts = issued + rejected
        shed_frac = sheds / attempts if attempts else 0.0
        # trailing offered rate (the open-loop demand, rejected included)
        win = 0.5
        recent = sum(1 for ta in offered_t if ta > now - win)
        rate = recent / min(win, now) if now > 0 else 0.0
        svc = engine.predicted_service_seconds(
            output_tokens=max(1, int(round(mean_out))))
        headroom = None if svc is None else 1.0 - rate * svc
        if headroom is not None:
            m_headroom.set(headroom)
            m_overload.set(1.0 if headroom <= 0.0 else 0.0)
            headroom_floor = (headroom if headroom_floor is None
                              else min(headroom_floor, headroom))
        sched = getattr(engine, "scheduler", None)
        timeline.append({
            "t": round(now, 4), "issued": issued, "rejected": rejected,
            "finished": done, "good": good, "shed_frac": round(
                shed_frac, 4),
            "offered_rps": round(rate, 2),
            "service_s": svc, "headroom": headroom,
            "brownout": None if sched is None else int(sched.level),
            "preemptions": (None if sched is None
                            else int(sched.preempt_requests)),
        })
        if sampler is not None:
            # TSDB tick on the schedule clock (deterministic per run
            # timing; a failed tick degrades the plane, never the run)
            sampler.sample(now)

    while True:
        now = time.perf_counter() - t0
        ticks += 1
        try:
            fault_point("serve.loadgen_tick", scenario=scenario.name)
        except Exception:   # noqa: BLE001 — clock skew model: skip + count
            ticks_skipped += 1
            m_skipped.inc()
            continue        # arrivals with t <= now re-issue next tick
        while idx < len(schedule) and schedule[idx]["t"] <= now:
            a = schedule[idx]
            idx += 1
            offered_t.append(now)
            prompt = _prompt_tokens(a["prompt_seed"], a["prompt_len"],
                                    vocab)
            if scenario.shared_prefix_len > 0:
                prompt = np.concatenate([
                    _tenant_prefix(scenario.name, a["tenant"],
                                   scenario.shared_prefix_len, vocab),
                    prompt])
            try:
                engine.add_request(
                    prompt, max_new_tokens=a["output_tokens"],
                    do_sample=scenario.do_sample,
                    temperature=scenario.temperature,
                    top_k=scenario.top_k, top_p=scenario.top_p,
                    seed=a["sample_seed"],
                    deadline_s=scenario.deadline_s, tenant=a["tenant"],
                    priority=a.get("priority", "interactive"),
                    # adapter-less arrivals keep the pre-round-22 call
                    # frame (engine doubles without the kwarg still work)
                    **({"adapter": a["adapter"]}
                       if a.get("adapter") else {}))
                issued += 1
                m_arrivals.inc()
            except BackpressureError:
                rejected += 1
        if engine.has_work():
            engine.step()
        elif idx < len(schedule):
            # ahead of the schedule: yield briefly instead of spinning
            time.sleep(min(0.002,
                           max(0.0, schedule[idx]["t"] - now)))
        if now >= next_sample:
            sample(now)
            next_sample = now + float(sample_every_s)
        if idx >= len(schedule) and not (drain and engine.has_work()):
            break
        if now > max_wall:
            break

    t1 = time.perf_counter()
    sample(t1 - t0)
    snap1 = _snapshot(reg)
    slo_eng.observe(snap1, t1)
    verdict = slo_eng.evaluate(emit=True)

    finished = {}
    tenants = {}
    classes = {}
    class_ttfts: dict[str, list] = {}
    for r in engine.finished.values():
        finished[r.finish_reason] = finished.get(r.finish_reason, 0) + 1
        trow = tenants.setdefault(r.tenant, {"finished": 0, "good": 0})
        trow["finished"] += 1
        trow["good"] += int(r.finish_reason in GOOD_REASONS)
        cls = getattr(r, "priority", "interactive")
        crow = classes.setdefault(cls, {"finished": 0, "good": 0})
        crow["finished"] += 1
        crow["good"] += int(r.finish_reason in GOOD_REASONS)
        if r.t_first is not None:
            class_ttfts.setdefault(cls, []).append(
                r.t_first - r.t_arrival)
    for cls, ts in class_ttfts.items():
        ts.sort()
        classes[cls]["ttft_p50"] = round(ts[int(0.5 * (len(ts) - 1))], 6)
        classes[cls]["ttft_p95"] = round(ts[int(0.95 * (len(ts) - 1))], 6)
    total_done = sum(finished.values())
    good = sum(finished.get(rn, 0) for rn in GOOD_REASONS)

    phases_report = phases.report() if phases.enabled else None
    cost = {"programs": engine.predicted_costs(),
            "ratio": _gauge_samples(snap1, "pir_cost_ratio")}

    # speculative-decode evidence: this run's draft/accept deltas (None
    # when the engine isn't speculative). One run = one scenario, so
    # this IS the per-scenario acceptance the drafting table is tuned on
    drafted = (_counter_total(snap1, "serving_draft_tokens_total")
               - _counter_total(snap0, "serving_draft_tokens_total"))
    accepted = (_counter_total(snap1, "serving_accepted_tokens_total")
                - _counter_total(snap0, "serving_accepted_tokens_total"))
    custom = getattr(engine, "_drafter", None)
    speculative = None
    if drafted > 0:
        speculative = {
            "drafter": (getattr(custom, "label", "custom")
                        if custom is not None
                        else f"ngram:{getattr(engine, 'draft_ngram', '?')}"),
            "draft_depth": int(getattr(engine, "draft_depth", 0)),
            "draft_tokens": int(drafted),
            "accepted_tokens": int(accepted),
            "acceptance": round(accepted / drafted, 4),
        }

    # prefix-cache evidence: this run's hit/miss/saved deltas (None when
    # the engine has no prefix index). hit_rate is per-ADMISSION — a
    # deferred-then-admitted request counts once, a faulted lookup
    # counts as a miss (the degrade-to-miss contract)
    prefix = None
    if getattr(engine, "_prefix", None) is not None:
        p_hits = (_counter_total(snap1, "serving_prefix_hits_total")
                  - _counter_total(snap0, "serving_prefix_hits_total"))
        p_miss = (_counter_total(snap1, "serving_prefix_misses_total")
                  - _counter_total(snap0, "serving_prefix_misses_total"))
        p_saved = (
            _counter_total(snap1, "serving_prefix_tokens_saved_total")
            - _counter_total(snap0, "serving_prefix_tokens_saved_total"))
        lookups = p_hits + p_miss
        prefix = {
            "hits": int(p_hits),
            "misses": int(p_miss),
            "hit_rate": (round(p_hits / lookups, 4) if lookups else None),
            "tokens_saved": int(p_saved),
            "shared_blocks": int(_counter_total(
                snap1, "serving_prefix_shared_blocks")),
            "evictions": int(
                _counter_total(snap1, "serving_prefix_evictions_total")
                - _counter_total(snap0, "serving_prefix_evictions_total")),
            "cow_forks": int(
                _counter_total(snap1, "serving_prefix_cow_forks_total")
                - _counter_total(snap0, "serving_prefix_cow_forks_total")),
        }

    # multi-adapter evidence (None unless the scenario names adapters):
    # run-window hot-load/evict counts, the per-adapter latency split,
    # and swap_recompiles — the jit_retrace_total delta, which warmup
    # pins to "adapter churn only" and the hot-swap contract pins to 0
    adapters_block = None
    if wants_adapters:
        t0b = _hist_cum_by(snap0, "serving_adapter_ttft_seconds", "adapter")
        t1b = _hist_cum_by(snap1, "serving_adapter_ttft_seconds", "adapter")
        p0b = _hist_cum_by(snap0, "serving_adapter_tpot_seconds", "adapter")
        p1b = _hist_cum_by(snap1, "serving_adapter_tpot_seconds", "adapter")
        per = {}
        for nm in sorted(set(t1b) | set(p1b)):
            row = {}
            td = _hist_delta(t1b.get(nm, {}), t0b.get(nm, {}))
            if td and td[-1][1]:
                q = quantiles_from_cumulative(td)
                row.update(ttft_count=int(td[-1][1]),
                           ttft_p50=q.get(0.5), ttft_p95=q.get(0.95))
            pd = _hist_delta(p1b.get(nm, {}), p0b.get(nm, {}))
            if pd and pd[-1][1]:
                q = quantiles_from_cumulative(pd)
                row.update(tpot_count=int(pd[-1][1]),
                           tpot_p50=q.get(0.5), tpot_p95=q.get(0.95))
            if row:
                per[nm] = row
        stats = [s.stats() for s in
                 (getattr(e, "adapters", None) for e in _engines_of(engine))
                 if s is not None]
        adapters_block = {
            "population": int(scenario.adapter_population),
            "names": adapter_names,
            "loads": int(
                _counter_total(snap1, "serving_adapter_loads_total")
                - _counter_total(snap0, "serving_adapter_loads_total")),
            "evictions": int(
                _counter_total(snap1, "serving_adapter_evictions_total")
                - _counter_total(snap0,
                                 "serving_adapter_evictions_total")),
            "load_failures": int(
                _counter_total(snap1,
                               "serving_adapter_load_failures_total")
                - _counter_total(snap0,
                                 "serving_adapter_load_failures_total")),
            "resident": sum(s["resident"] for s in stats),
            "swap_recompiles": int(
                _counter_total(snap1, "jit_retrace_total")
                - _counter_total(snap0, "jit_retrace_total")),
            "per_adapter": per,
        }

    report = {
        "format": REPORT_FORMAT,
        "scenario": scenario.name,
        "seed": int(seed),
        "schedule": {"arrivals": len(schedule),
                     "digest": schedule_digest(schedule),
                     "duration_s": dur,
                     "mean_output_tokens": round(mean_out, 2)},
        "wall_s": round(t1 - t0, 4),
        "issued": issued,
        "rejected": rejected,
        "ticks": ticks,
        "ticks_skipped": ticks_skipped,
        "finished": finished,
        "goodput": round(good / total_done, 4) if total_done else None,
        "goodput_rps": round(good / (t1 - t0), 2),
        "shed": finished.get("shed", 0) + rejected,
        "timeouts": finished.get("timeout", 0),
        "ttft": _quantile_block(snap0, snap1, "serving_ttft_seconds"),
        "tpot": _quantile_block(snap0, snap1, "serving_tpot_seconds"),
        "tenants": tenants,
        "classes": classes,
        "slo": verdict,
        "phases": phases_report,
        "coverage": (phases_report or {}).get("coverage"),
        "cost": cost,
        "speculative": speculative,
        "prefix": prefix,
        "adapters": adapters_block,
        "headroom_floor": headroom_floor,
        "timeline": timeline,
        # scheduler evidence (all zero/None for a scheduler-less engine):
        # end-of-run brownout level must be 0 — check_report gates it
        "brownout_level_end": _gauge_samples(
            snap1, "serving_brownout_level").get("-", 0.0),
        "brownout_transitions": (
            _counter_total(snap1, "serving_brownout_transitions_total")
            - _counter_total(snap0, "serving_brownout_transitions_total")),
        "preemptions": (
            _counter_total(snap1, "serving_preemptions_total")
            - _counter_total(snap0, "serving_preemptions_total")),
        "quota_deferrals": (
            _counter_total(snap1, "serving_quota_deferrals_total")
            - _counter_total(snap0, "serving_quota_deferrals_total")),
        "scheduler": (None if getattr(engine, "scheduler", None) is None
                      else {"level_end": int(engine.scheduler.level),
                            "fifo": bool(engine.scheduler.fifo),
                            "preempts": int(
                                engine.scheduler.preempt_requests)}),
        # mesh evidence (None for a single engine): per-replica
        # goodput/headroom snapshots + handoff/failover accounting from
        # MeshRouter.mesh_report() — the engine surface is identical,
        # so the harness only needs this one hook
        "mesh": (engine.mesh_report()
                 if hasattr(engine, "mesh_report") else None),
        # embedded-TSDB evidence (None when the plane is off): per-rule
        # latest value + point counts, series/sample totals, degradation
        "timeseries": sampler.summary() if sampler is not None else None,
    }
    rec = _get_recorder()
    if rec.enabled:
        rec.record("profile", scenario=scenario.name, seed=int(seed),
                   issued=issued, goodput=report["goodput"],
                   coverage=report["coverage"],
                   slo_ok=verdict.get("ok"))
    return report


def check_report(report, min_coverage=0.95, min_acceptance=None,
                 require_timeseries=False, require_autoscale=False,
                 min_prefix_hit_rate=None, min_adapter_loads=None):
    """Acceptance gate over a run report -> list of problems (empty =
    pass). Checked: an SLO verdict exists, phase attribution covers at
    least `min_coverage` of engine wall time, the cost model priced at
    least one dispatched program (predicted-vs-measured gauge is
    populated), every finished request carries a known finish reason,
    and the brownout ladder returned to level 0 by end of run (a run
    that leaves the engine degraded is not a pass). `min_acceptance`
    (speculative runs only) additionally requires a speculative block
    with draft acceptance at or above the floor. `require_timeseries`
    gates the observability plane: a timeseries block must exist, not
    be degraded, and every recording rule must have >= 1 populated
    point. `require_autoscale` (mesh runs) requires an internally
    consistent autoscale verdict (autoscale.check_verdict).
    `min_prefix_hit_rate` (prefix-cache runs) requires a prefix block
    with admission hit_rate at or above the floor and tokens actually
    saved — a warm shared-prefix run that saved nothing is a broken
    index, not a pass. `min_adapter_loads` (multi-adapter runs) requires
    an adapters block whose run-window hot-loads meet the floor, whose
    per-adapter latency split is populated, and — the hot-swap contract
    — whose swap_recompiles is exactly 0: adapter churn that recompiles
    the fused programs is a regression, however good the latency."""
    problems = []
    if min_adapter_loads is not None:
        ad = report.get("adapters")
        if not ad:
            problems.append("no adapters block in report "
                            "(scenario not multi-adapter?)")
        else:
            # the brownout ladder legally constructs at most one new
            # decode program per transition (decode_steps is part of
            # the compile key); only the excess is adapter churn
            allowed = int(report.get("brownout_transitions") or 0)
            if ad.get("swap_recompiles", 0) > allowed:
                problems.append(
                    f"adapter hot-swap recompiled: jit_retrace_total "
                    f"moved by {ad['swap_recompiles']} inside the run "
                    f"window (contract: 0 beyond the {allowed} brownout "
                    f"program swaps)")
            if ad.get("loads", 0) < float(min_adapter_loads):
                problems.append(
                    f"adapter hot-loads {ad.get('loads')} < "
                    f"{min_adapter_loads}")
            if not ad.get("per_adapter"):
                problems.append("per-adapter latency split is empty "
                                "(adapter histograms never observed)")
    if min_prefix_hit_rate is not None:
        pfx = report.get("prefix")
        if not pfx:
            problems.append("no prefix block in report "
                            "(engine prefix cache off?)")
        else:
            if (pfx.get("hit_rate") or 0.0) < float(min_prefix_hit_rate):
                problems.append(
                    f"prefix hit_rate {pfx.get('hit_rate')} < "
                    f"{min_prefix_hit_rate}")
            if pfx.get("tokens_saved", 0) <= 0:
                problems.append("prefix cache saved no prefill tokens")
    if require_timeseries:
        ts = report.get("timeseries")
        if not isinstance(ts, dict):
            problems.append("no timeseries block in report (plane off?)")
        else:
            if ts.get("degraded"):
                problems.append("observability plane degraded during run")
            rules = ts.get("rules") or {}
            empty = sorted(n for n in RECORDING_RULES
                           if not (rules.get(n) or {}).get("points"))
            if empty:
                problems.append(
                    f"recording rules with no populated series: {empty}")
    if require_autoscale:
        verdict = (report.get("mesh") or {}).get("autoscale")
        if verdict is None:
            problems.append("no autoscale verdict in mesh report")
        else:
            problems.extend(f"autoscale: {p}"
                            for p in _check_autoscale(verdict))
    if min_acceptance is not None:
        spec = report.get("speculative")
        if not spec:
            problems.append("no speculative block in report "
                            "(engine not speculative / no drafts issued)")
        elif spec.get("acceptance", 0.0) < float(min_acceptance):
            problems.append(
                f"draft acceptance {spec.get('acceptance')} < "
                f"{min_acceptance} (drafter {spec.get('drafter')})")
    slo_v = report.get("slo")
    if not isinstance(slo_v, dict) or "ok" not in slo_v:
        problems.append("no SLO verdict in report")
    cov = report.get("coverage")
    if cov is None:
        problems.append("no phase-attribution coverage "
                        "(profiler disabled?)")
    elif cov < float(min_coverage):
        problems.append(f"phase attribution coverage {cov:.3f} "
                        f"< {min_coverage}")
    if not report.get("cost", {}).get("ratio"):
        problems.append("pir_cost_ratio gauge not populated "
                        "(no measured dispatch priced)")
    if not report.get("issued"):
        problems.append("no requests issued")
    unknown = sorted(set(report.get("finished") or {})
                     - set(KNOWN_FINISH_REASONS))
    if unknown:
        problems.append(
            f"requests finished with unknown reason(s): {unknown}")
    sched = report.get("scheduler")
    level_end = (sched or {}).get("level_end",
                                  report.get("brownout_level_end"))
    if level_end:
        problems.append(
            f"serving_brownout_level did not return to 0 by end of run "
            f"(level {level_end})")
    return problems
