"""Inference engine. reference: python/paddle/inference/ re-exporting
Config/Predictor from libpaddle (C++ AnalysisPredictor,
paddle/fluid/inference/api/analysis_predictor.h:105).

TPU-native: the inference "program" is the StableHLO artifact produced by
jit.save; "analysis passes" (fusion, mixed precision convert —
paddle/fluid/inference/analysis/passes/) are XLA's job at AOT-compile time.
Config keeps the reference's knob surface; Predictor keeps the
zero-copy handle API (get_input_handle/run/get_output_handle).
"""

from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp
import numpy as np

from .adapters import (  # noqa: E402,F401
    AdapterLoadError, AdapterStore, LoraWeights, demo_store_for_engine,
    make_demo_store, per_adapter_slos)
from .loadgen import (  # noqa: E402,F401
    SCENARIOS, Scenario, build_schedule, check_report, run_scenario)
from .scheduler import (  # noqa: E402,F401
    BROWNOUT_LEVELS, PRIORITY_CLASSES, SLOScheduler)
from .serving import (  # noqa: E402,F401
    BackpressureError, ContinuousBatchingEngine, KVPoolExhaustedError,
    Request)
from .mesh import (  # noqa: E402,F401
    KVHandoffError, MeshRouter, ReplicaPool)

__all__ = ["ContinuousBatchingEngine", "Request", "BackpressureError",
           "KVPoolExhaustedError",
           "AdapterStore", "AdapterLoadError", "LoraWeights",
           "make_demo_store", "demo_store_for_engine", "per_adapter_slos",
           "Scenario", "SCENARIOS", "build_schedule", "run_scenario",
           "check_report",
           "SLOScheduler", "PRIORITY_CLASSES", "BROWNOUT_LEVELS",
           "MeshRouter", "ReplicaPool", "KVHandoffError",
           "Config", "Predictor", "Tensor", "create_predictor",
           "PrecisionType", "PlaceType", "get_version"]


def get_version():
    from .. import __version__
    return __version__


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM = 3


class Config:
    """reference: paddle/fluid/inference/api/paddle_analysis_config.h.
    Knobs that don't apply on TPU (TensorRT, MKLDNN…) are accepted and
    recorded so reference code runs unchanged."""

    def __init__(self, prog_file=None, params_file=None):
        self._prefix = (prog_file[:-len(".pdmodel")]
                        if prog_file and prog_file.endswith(".pdmodel")
                        else prog_file)
        self._params_path = params_file
        self._precision = PrecisionType.Float32
        self._device = "tpu"
        self._enable_memory_optim = True
        self._flags = {}

    def set_model(self, prog_file, params_file=None):
        self._prefix = (prog_file[:-len(".pdmodel")]
                        if prog_file.endswith(".pdmodel") else prog_file)
        if params_file is not None:
            self._params_path = params_file

    def model_dir(self):
        return self._prefix

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=None):
        self._device = "tpu"  # accelerator == TPU in this build

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "tpu"

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def enable_mkldnn(self):
        self._flags["mkldnn"] = True

    def enable_tensorrt_engine(self, **kwargs):
        self._flags["tensorrt"] = kwargs  # recorded; XLA owns fusion on TPU

    def switch_ir_optim(self, flag=True):
        self._flags["ir_optim"] = flag

    def switch_use_feed_fetch_ops(self, flag=False):
        pass

    def switch_specify_input_names(self, flag=True):
        pass

    def set_cpu_math_library_num_threads(self, n):
        self._flags["cpu_threads"] = n

    def enable_low_precision(self, precision=PrecisionType.Bfloat16):
        self._precision = precision

    def summary(self):
        return {"model": self._prefix, "device": self._device,
                "precision": self._precision, **self._flags}


class Tensor:
    """Zero-copy I/O handle. reference:
    paddle/fluid/inference/api/paddle_tensor.h ZeroCopyTensor."""

    def __init__(self, name, shape=None, dtype=np.float32):
        self._name = name
        self._value = None
        self._shape = shape
        self._dtype = dtype

    def name(self):
        return self._name

    def copy_from_cpu(self, data):
        self._value = np.ascontiguousarray(data)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def reshape(self, shape):
        self._shape = tuple(shape)

    def shape(self):
        if self._value is not None:
            return list(self._value.shape)
        return list(self._shape or [])

    def type(self):
        return self._dtype


class Predictor:
    """reference: paddle/fluid/inference/api/paddle_inference_api.h
    Predictor over an AOT-compiled StableHLO program."""

    def __init__(self, config: Config):
        self._config = config
        prefix = config._prefix
        params_path = config._params_path or prefix + ".pdiparams"
        with open(prefix + ".pdmodel", "rb") as f:
            meta = pickle.load(f)
        from ..jit import FORMAT_VERSION, _load_npz_params
        version = meta.get("format_version", 1)
        if version > FORMAT_VERSION:
            raise ValueError(
                f"{prefix}.pdmodel has format version {version}; this build "
                f"reads <= {FORMAT_VERSION}. Use a newer paddle_tpu or "
                "re-export the model.")
        if version >= 2:  # npz params (jit.save v2)
            self._params = _load_npz_params(params_path, meta)
        else:  # v1: pickled dict
            with open(params_path, "rb") as f:
                self._params = pickle.load(f)
        if not meta.get("stablehlo"):
            raise ValueError(
                f"{prefix}.pdmodel holds no serialized program; re-export "
                "with paddle_tpu.jit.save(layer, path, input_spec=[...])")
        self._exported = jax.export.deserialize(meta["stablehlo"])
        self._input_spec = meta.get("input_spec", [])
        self._input_names = [f"x{i}" for i in range(len(self._input_spec))]
        self._inputs = {n: Tensor(n, shape=tuple(s[0]), dtype=s[1])
                        for n, s in zip(self._input_names, self._input_spec)}
        self._outputs = []
        # enable_low_precision note: the serialized program's calling
        # convention pins param/input dtypes, so post-export casting is
        # invalid. On TPU, f32 matmuls already execute on the MXU with
        # bf16 passes (XLA default precision), which is the effect the
        # reference's mixed-precision convert pass targets.

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def run(self, inputs=None):
        if inputs is not None:  # list-style API
            arrs = [np.asarray(getattr(x, "_value", x)) for x in inputs]
        else:
            arrs = [self._inputs[n]._value for n in self._input_names]
            if any(a is None for a in arrs):
                missing = [n for n in self._input_names
                           if self._inputs[n]._value is None]
                raise ValueError(f"inputs not set: {missing}")
        out = self._exported.call(self._params, *arrs)
        flat = jax.tree_util.tree_leaves(out)
        self._outputs = []
        for i, o in enumerate(flat):
            t = Tensor(f"out{i}")
            t._value = np.asarray(o)
            self._outputs.append(t)
        if inputs is not None:
            return self._outputs
        return True

    def get_output_names(self):
        return [t.name() for t in self._outputs]

    def get_output_handle(self, name):
        for t in self._outputs:
            if t.name() == name:
                return t
        if not self._outputs:
            raise RuntimeError("no outputs yet — call run() first")
        raise KeyError(name)

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    """reference: paddle_infer::CreatePredictor (SURVEY.md §3.5)."""
    return Predictor(config)
