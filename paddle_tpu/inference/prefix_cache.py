"""Cross-request prefix cache index: block-granular prompt-prefix
sharing for the paged KV pool (round 18).

Production chat/RAG traffic repeats prompt prefixes (system prompts,
few-shot templates, retrieval contexts). The serving engine keys a
radix-style index by a CHAINED rolling hash over block-aligned token
chunks: chunk i's key is blake2b(key[i-1] || tokens[i*bs:(i+1)*bs]), so
a key identifies the entire prefix up to and including its chunk — two
prompts share a node if and only if they share every token before it.
The chain is seeded with an identity string (model dtype + KV block
format + block size), so an engine whose pool format changes (e.g. the
serve.kv_dequant degradation re-encodes the pool) can never resolve a
stale entry from the old byte layout — the engine clears the index on
any such transition.

Each node owns ONE pool block id. The pool's refcounting pins it: the
index holds a +1 reference, every request that adopts it at admission
holds another, and the block returns to the free list only when the
last reference drops. Blocks in the index are immutable by
construction — only fully-prompt-covered blocks are ever inserted
(decode and speculative-draft writes land at positions >= the prompt
length, i.e. in later blocks), and the one case where a tail prefill
must write inside a shared block (a block-aligned full-prefix match
still re-runs the final prompt position for the first-token logits) is
handled by the pool's copy-on-write fork BEFORE the write.

Hash collisions cannot corrupt streams: every node stores its chunk's
raw tokens and lookup/insert verify them — a mismatch is treated as a
miss, never as a hit.

Eviction is LRU over LEAF nodes (insert/lookup touch every node on the
path, so a parent is always at least as recent as its children);
evicting a node only drops the index's pin — a block still referenced
by a resident request stays until that request finishes.
"""

from __future__ import annotations

import hashlib

__all__ = ["PrefixCacheIndex", "chain_keys", "affinity_key"]


def chain_keys(identity, block_size, prompt):
    """The chained chunk keys for a prompt: one blake2b digest per FULL
    block-aligned chunk, each folding in the previous key so key i
    commits to every token before position (i+1)*block_size. Yields
    (key, chunk) pairs; `chunk` is the numpy token slice (for the
    collision check)."""
    h = hashlib.blake2b(identity.encode(), digest_size=16).digest()
    for i in range(int(prompt.size) // block_size):
        chunk = prompt[i * block_size:(i + 1) * block_size]
        h = hashlib.blake2b(h + chunk.tobytes(),
                            digest_size=16).digest()
        yield h, chunk


def affinity_key(identity, block_size, prompt):
    """The FIRST chunk's chain key (None for prompts shorter than one
    block) — the mesh router's prefix-affinity hint: requests whose
    prompts share their leading block hash to the same key and prefer
    the replica whose index already holds that prefix."""
    for key, _chunk in chain_keys(identity, block_size, prompt):
        return key
    return None


class _Node:
    __slots__ = ("key", "block", "tokens", "parent", "children",
                 "last_use")

    def __init__(self, key, block, tokens, parent, last_use):
        self.key = key
        self.block = block
        self.tokens = tokens          # raw chunk bytes: collision check
        self.parent = parent          # parent key (None at depth 0)
        self.children = set()         # child keys
        self.last_use = last_use


class PrefixCacheIndex:
    """identity: string folded into every chain key (model/format/block
    identity — entries can never resolve across a byte-layout change).
    max_blocks: optional hard cap on indexed blocks; inserts past it
    evict LRU leaves. The index never touches the pool itself — lookup/
    insert/evict return block ids and the ENGINE adjusts the pool's
    refcounts (pin/unpin), so this stays a pure host-side structure."""

    def __init__(self, identity, block_size, max_blocks=None):
        self.identity = str(identity)
        self.block_size = int(block_size)
        self.max_blocks = None if max_blocks is None else int(max_blocks)
        self._nodes: dict[bytes, _Node] = {}
        self._clock = 0

    def __len__(self):
        return len(self._nodes)

    def _touch(self, node):
        self._clock += 1
        node.last_use = self._clock

    def lookup(self, prompt):
        """Longest indexed prefix of `prompt`: ([block ids], matched
        tokens). Only FULL blocks match (matched tokens is always a
        multiple of block_size, possibly == prompt.size for a
        block-aligned full-prompt hit — the engine clamps the prefill
        tail to keep >= 1 real position). Touches every matched node
        (LRU recency)."""
        blocks = []
        for key, chunk in chain_keys(self.identity, self.block_size,
                                     prompt):
            node = self._nodes.get(key)
            if node is None or node.tokens != chunk.tobytes():
                break
            self._touch(node)
            blocks.append(node.block)
        return blocks, len(blocks) * self.block_size

    def insert(self, prompt, table):
        """Index every full-prompt block of a just-prefilled request:
        chunk i's node points at table[i]. Existing nodes are kept
        (their block already holds identical bytes) and touched; new
        nodes adopt the request's block. Returns the block ids of the
        NEW nodes — the caller pins each one (+1 refcount) so the block
        outlives the request."""
        new_blocks = []
        parent = None
        for i, (key, chunk) in enumerate(
                chain_keys(self.identity, self.block_size, prompt)):
            node = self._nodes.get(key)
            if node is not None:
                if node.tokens != chunk.tobytes():
                    break               # collision: never alias content
                self._touch(node)
            else:
                self._clock += 1
                node = _Node(key, int(table[i]), chunk.tobytes(),
                             parent, self._clock)
                self._nodes[key] = node
                if parent is not None and parent in self._nodes:
                    self._nodes[parent].children.add(key)
                new_blocks.append(node.block)
            parent = key
        return new_blocks

    def _remove(self, key):
        node = self._nodes.pop(key)
        if node.parent is not None and node.parent in self._nodes:
            self._nodes[node.parent].children.discard(key)
        return node.block

    def evict(self, protect=frozenset()):
        """Drop the least-recently-used LEAF node whose block is not in
        `protect` (blocks an in-flight admission is about to adopt).
        Returns the evicted block id (the caller unpins it), or None
        when nothing is evictable."""
        victim = None
        for key, node in self._nodes.items():
            if node.children or node.block in protect:
                continue
            if victim is None or node.last_use < victim[1].last_use:
                victim = (key, node)
        if victim is None:
            return None
        return self._remove(victim[0])

    def trim(self, protect=frozenset()):
        """Evict down to max_blocks (no-op when uncapped). Returns the
        list of unpinned block ids."""
        out = []
        if self.max_blocks is None:
            return out
        while len(self._nodes) > self.max_blocks:
            b = self.evict(protect)
            if b is None:
                break
            out.append(b)
        return out

    def clear(self):
        """Drop every entry (format/layout change: the stored bytes no
        longer mean what the keys promise). Returns all block ids for
        the caller to unpin."""
        blocks = [n.block for n in self._nodes.values()]
        self._nodes.clear()
        return blocks
