"""Overload-safe SLO scheduler for the continuous-batching engine.

PR 10 landed the sensing half of the SLO loop (seeded loadgen, phase
attribution, the `slo_headroom` / `serving_overload` gauges); this
module is the acting half. It closes the loop with three mechanisms,
each driven by the signals the engine already emits:

  - **priority classes + preemption**: requests carry one of the
    PRIORITY_CLASSES below; when interactive traffic is waiting and the
    engine is under SLO pressure, a batch/best_effort decode lane is
    preempted. The paged-KV blocks stay resident and the host decode
    cursor is parked, so the lane later resumes through the
    membership-change upload path with a byte-identical stream — no
    re-prefill, no re-decode.
  - **per-tenant fairness + quotas**: admission order comes from a
    deficit-round-robin walk over per-tenant sub-queues (keyed by the
    bounded-cardinality tenant label), with an optional per-tenant lane
    quota; a quota'd tenant's requests stay queued and the deferral is
    counted (`serving_quota_deferrals_total{tenant}`).
  - **brownout ladder**: a closed, ordered registry of degradation
    levels (BROWNOUT_LEVELS). TTFT/TPOT observations and the cost-model
    headroom drive one-level-at-a-time escalation and — with hysteresis
    — recovery. Every transition is counted
    (`serving_brownout_transitions_total{direction}`), gauged
    (`serving_brownout_level`), and recorded in the flight recorder.

Failure contract (the `serve.sched_decide` fault site): ANY exception
out of the per-step decision degrades scheduling to plain FIFO for the
engine's lifetime — brownout knobs restored, preempted lanes resumed,
admission back to first-come-first-served. The engine never deadlocks
and never drops a lane because its scheduler broke.

Both registries are **closed**: the static checker's scheduler-actions
rule pins every priority/brownout literal used in serving/scheduler
code to these dicts, and both must match the RESILIENCE.md "Overload
runbook" tables in both directions.
"""

from __future__ import annotations

import time
from collections import deque

from ..observability.catalog import metric as _metric
from ..observability.recorder import get_recorder as _get_recorder
from ..observability.slo import DEFAULT_SLOS
from ..resilience.faults import fault_point

__all__ = ["PRIORITY_CLASSES", "BROWNOUT_LEVELS", "SLOScheduler",
           "level_index", "level_name"]

# Closed registry of request priority classes, ordered by admission
# precedence (first = most latency-sensitive). The dict literal is
# parsed by tools/static_check.py's scheduler-actions rule.
PRIORITY_CLASSES = {
    "interactive": "latency-sensitive user traffic: admitted first, "
                   "never preempted, its TTFT/TPOT drive the ladder",
    "batch": "throughput traffic: admitted after interactive, decode "
             "lanes preemptible under SLO pressure",
    "best_effort": "scavenger traffic: admitted last, preempted first, "
                   "shed outright at the deepest brownout level",
}

# Closed, ORDERED registry of brownout degradation levels. Index order
# IS severity order; each level's actions are cumulative with every
# level above it. All knob changes are reversible on recovery — unlike
# the fault-driven degradations (speculation_off, kv_bf16), which are
# permanent for the engine's lifetime.
BROWNOUT_LEVELS = {
    "normal": "no degradation: base decode_steps/draft_depth, "
              "speculation as configured",
    "shrink_decode_steps": "halve the fused-scan K so occupancy "
                           "changes (admission, preemption) take "
                           "effect with half the dispatch latency",
    "reduce_draft_depth": "drop speculative draft_depth to 1: less "
                          "verify work per dispatch under pressure",
    "disable_speculation": "turn speculation off (reversibly): decode "
                           "reverts to the plain fused program",
    "force_small_prefill_chunk": "plan new admissions' prefill at the "
                                 "smallest compiled chunk width so "
                                 "decode lanes wait behind shorter "
                                 "prefill pieces (reshape, not shed)",
    "cap_max_new_tokens": "clamp newly admitted requests' "
                          "max_new_tokens to the scheduler's mnt_cap: "
                          "shorter streams drain backlog faster; the "
                          "stream still serves (reshape, not shed)",
    "shed_best_effort": "stop serving best_effort: queued best_effort "
                        "requests finish with finish_reason='shed' at "
                        "admission",
}

_LEVEL_ORDER = tuple(BROWNOUT_LEVELS)
MAX_LEVEL = len(_LEVEL_ORDER) - 1

# preemption victim order: higher rank = preempted first
_PRIO_RANK = {name: i for i, name in enumerate(PRIORITY_CLASSES)}


def level_index(name):
    """Index of a brownout level in the closed registry. Raises KeyError
    on an unknown name — the registry is closed, same discipline as the
    metric catalog. String-literal call sites are linted against
    BROWNOUT_LEVELS by the scheduler-actions rule."""
    try:
        return _LEVEL_ORDER.index(name)
    except ValueError:
        raise KeyError(
            f"unknown brownout level {name!r}; registered: "
            f"{list(_LEVEL_ORDER)}") from None


def level_name(idx):
    """Registry name of a brownout level index."""
    return _LEVEL_ORDER[int(idx)]


# ladder rungs referenced by _apply(); resolved once through the closed
# registry so a registry rename cannot silently desynchronize the knobs
_IDX_SHRINK = level_index("shrink_decode_steps")
_IDX_DRAFT = level_index("reduce_draft_depth")
_IDX_NOSPEC = level_index("disable_speculation")
_IDX_SMALL_CHUNK = level_index("force_small_prefill_chunk")
_IDX_CAP_MNT = level_index("cap_max_new_tokens")
_IDX_SHED = level_index("shed_best_effort")


def _pctl(values, q):
    """Deterministic host-side quantile over a small window (sorted
    nearest-rank); None when the window is empty."""
    if not values:
        return None
    s = sorted(values)
    return s[int(q * (len(s) - 1))]


class _Signals:
    """One step's scheduling inputs, separated from the engine so
    `decide()` is unit-testable without a model."""

    __slots__ = ("headroom", "ttft_p95", "tpot_p99", "queued_interactive",
                 "free_lanes")

    def __init__(self, headroom=None, ttft_p95=None, tpot_p99=None,
                 queued_interactive=0, free_lanes=0):
        self.headroom = headroom
        self.ttft_p95 = ttft_p95
        self.tpot_p99 = tpot_p99
        self.queued_interactive = queued_interactive
        self.free_lanes = free_lanes


def _default_target(name):
    spec = next((s for s in DEFAULT_SLOS if s.name == name), None)
    return None if spec is None else float(spec.objective)


class SLOScheduler:
    """Closed-loop admission/preemption/brownout policy for ONE engine.

    The engine calls `on_step(engine)` once per scheduling step (before
    admission), `pick_index(engine)` to choose which queued request to
    admit next, `should_resume(engine)` before re-admitting preempted
    lanes, and feeds TTFT/TPOT observations through `note_ttft` /
    `note_tpot`. All state is host-side and O(tenants + window); the
    scheduler never touches device arrays.

    Knobs:
      ttft_target / tpot_target: seconds; default from DEFAULT_SLOS
        (ttft_p95 / tpot_p99 objectives).
      quantum: DRR credit per tenant visit, in tokens (prompt +
        max_new_tokens is the cost unit — the same unit
        predicted_service_seconds prices).
      tenant_quota: max simultaneously-occupied lanes per tenant
        (None = unlimited).
      adapter_quota: max simultaneously-occupied lanes per NAMED LoRA
        adapter (None = unlimited; base-weight requests are exempt) —
        caps how much of the batch one hot finetune can pin, the same
        way tenant_quota caps a tenant.
      escalate_after / recover_after: consecutive bad/good decisions
        before a level transition (recovery is deliberately slower —
        hysteresis, so the ladder cannot flap).
      min_dwell: steps a level must hold before the NEXT transition.
      resume_margin: headroom above which preempted lanes resume even
        while interactive traffic is still queued.
      window: TTFT/TPOT observation window (per-signal deque length).
      rate_window_s: trailing window for the offered-arrival-rate
        estimate that feeds headroom.
      mnt_cap: max_new_tokens clamp applied to admissions while the
        cap_max_new_tokens rung is engaged (reshape, not shed).
    """

    def __init__(self, ttft_target=None, tpot_target=None, quantum=32.0,
                 tenant_quota=None, adapter_quota=None, escalate_after=2,
                 recover_after=4, min_dwell=2, resume_margin=0.25,
                 window=128, rate_window_s=0.5, mnt_cap=16):
        self.ttft_target = (float(ttft_target) if ttft_target is not None
                            else _default_target("ttft_p95"))
        self.tpot_target = (float(tpot_target) if tpot_target is not None
                            else _default_target("tpot_p99"))
        self.quantum = float(quantum)
        self.tenant_quota = (None if tenant_quota is None
                             else max(1, int(tenant_quota)))
        self.adapter_quota = (None if adapter_quota is None
                              else max(1, int(adapter_quota)))
        self.escalate_after = max(1, int(escalate_after))
        self.recover_after = max(1, int(recover_after))
        self.min_dwell = max(0, int(min_dwell))
        self.resume_margin = float(resume_margin)
        self.rate_window_s = float(rate_window_s)
        self.mnt_cap = max(1, int(mnt_cap))
        self.level = 0
        self.fifo = False           # True after a sched_decide failure
        self.shed_best_effort = False
        self.transitions_up = 0
        self.transitions_down = 0
        self.preempt_requests = 0
        self._ttft = deque(maxlen=int(window))
        self._tpot = deque(maxlen=int(window))
        self._bad = 0               # consecutive bad decisions
        self._good = 0              # consecutive good decisions
        self._dwell = self.min_dwell    # steps since last transition
        self._last_sig = None
        # DRR state: per-priority-class tenant ring + cursor, and a
        # (class, tenant) -> residual-credit map
        self._rings: dict[str, list[str]] = {}
        self._cursors: dict[str, int] = {}
        self._deficit: dict[tuple[str, str], float] = {}
        self._rec = _get_recorder()
        self._m_level = _metric("serving_brownout_level")
        self._m_level.set(float(self.level))

    # --- signal intake ---------------------------------------------------
    def note_ttft(self, seconds):
        self._ttft.append(float(seconds))

    def note_tpot(self, seconds):
        self._tpot.append(float(seconds))

    # --- the per-step decision -------------------------------------------
    def on_step(self, engine):
        """One closed-loop decision: collect signals, move the brownout
        ladder at most one level, and preempt at most one lane. ANY
        failure — including the serve.sched_decide fault site — degrades
        this scheduler to plain FIFO for the engine's lifetime; overload
        can break the policy, never the engine."""
        if self.fifo:
            return
        try:
            fault_point("serve.sched_decide", level=self.level)
            sig = self._collect(engine)
            self._last_sig = sig
            if self.decide(sig):
                self._apply(engine)
            self._maybe_preempt(engine, sig)
        except Exception as e:  # noqa: BLE001 — FIFO degrade, no deadlock
            self._degrade_fifo(engine, why=type(e).__name__)

    def _collect(self, engine):
        """Engine state -> _Signals. Headroom uses the engine's own
        trailing arrival rate (so the scheduler works without loadgen)
        against the calibrated cost model; TTFT/TPOT windows are fed by
        the engine's note_* hooks."""
        now = time.perf_counter()
        cutoff = now - self.rate_window_s
        recent = sum(1 for t in engine._arrivals if t > cutoff)
        svc = engine.predicted_service_seconds()
        headroom = None
        if svc is not None and recent:
            headroom = 1.0 - (recent / self.rate_window_s) * svc
        return _Signals(
            headroom=headroom,
            ttft_p95=_pctl(self._ttft, 0.95),
            tpot_p99=_pctl(self._tpot, 0.99),
            queued_interactive=sum(
                1 for r in engine.queue if r.priority == "interactive"),
            free_lanes=sum(1 for r in engine.lanes if r is None))

    def decide(self, sig):
        """Move the ladder at most ONE level for this step's signals.
        Escalation needs `escalate_after` consecutive bad steps,
        recovery `recover_after` consecutive good ones, and every
        transition starts a `min_dwell` cooldown — monotone one-rung
        moves with hysteresis, no flapping. Returns True when the level
        changed (caller re-applies the knobs)."""
        bad = ((sig.headroom is not None and sig.headroom <= 0.0)
               or (sig.ttft_p95 is not None
                   and self.ttft_target is not None
                   and sig.ttft_p95 > self.ttft_target)
               or (sig.tpot_p99 is not None
                   and self.tpot_target is not None
                   and sig.tpot_p99 > self.tpot_target))
        self._dwell += 1
        if bad:
            self._bad += 1
            self._good = 0
            if (self._bad >= self.escalate_after and self.level < MAX_LEVEL
                    and self._dwell > self.min_dwell):
                self._transition(self.level + 1, "up")
                return True
        else:
            self._good += 1
            self._bad = 0
            if (self._good >= self.recover_after and self.level > 0
                    and self._dwell > self.min_dwell):
                self._transition(self.level - 1, "down")
                return True
        return False

    def _transition(self, new_level, direction):
        self.level = int(new_level)
        self._dwell = 0
        self._bad = 0
        self._good = 0
        if direction == "up":
            self.transitions_up += 1
        else:
            self.transitions_down += 1
        _metric("serving_brownout_transitions_total",
                direction=direction).inc()
        self._m_level.set(float(self.level))
        if self._rec.enabled:
            self._rec.record("sched", action="brownout",
                             direction=direction, level=self.level,
                             name=level_name(self.level))

    def _apply(self, engine):
        """Re-derive every brownout knob from the current level —
        cumulative and REVERSIBLE: level 0 restores the engine's
        constructor-time base values (modulo permanent fault
        degradations, which the engine's setters respect)."""
        lvl = self.level
        base_k = engine._base_decode_steps
        engine._set_decode_steps(
            max(1, base_k // 2) if lvl >= _IDX_SHRINK else base_k)
        engine._set_draft_depth(
            1 if lvl >= _IDX_DRAFT else engine._base_draft_depth)
        engine._set_speculation(lvl < _IDX_NOSPEC)
        engine._set_prefill_chunk_small(lvl >= _IDX_SMALL_CHUNK)
        engine._set_mnt_cap(self.mnt_cap if lvl >= _IDX_CAP_MNT else None)
        self.shed_best_effort = lvl >= _IDX_SHED

    # --- preemption ------------------------------------------------------
    def _maybe_preempt(self, engine, sig):
        """Preempt at most one non-interactive decode lane per step,
        only when interactive traffic is actually waiting, no lane is
        free, and the engine is under pressure (non-positive headroom, a
        TTFT breach, or an already-engaged ladder)."""
        if not sig.queued_interactive or sig.free_lanes:
            return
        pressure = ((sig.headroom is not None and sig.headroom <= 0.0)
                    or (sig.ttft_p95 is not None
                        and self.ttft_target is not None
                        and sig.ttft_p95 > self.ttft_target)
                    or self.level > 0)
        if not pressure:
            return
        victims = [i for i in engine._decode_active()
                   if engine.lanes[i].priority != "interactive"]
        if not victims:
            return
        # preempt the lowest class first; among equals, the lane with
        # the most remaining work (it blocks the lane longest)
        victim = max(victims, key=lambda i: (
            _PRIO_RANK[engine.lanes[i].priority],
            engine.lanes[i].max_new_tokens
            - len(engine.lanes[i].generated), -i))
        if engine._try_preempt(victim, why="slo_pressure"):
            self.preempt_requests += 1

    def should_resume(self, engine):
        """Whether parked (preempted) requests may re-enter lanes this
        step: always once degraded to FIFO, when no interactive request
        is waiting for the lane, or when headroom has recovered past the
        resume margin."""
        if self.fifo:
            return True
        if not any(r.priority == "interactive" for r in engine.queue):
            return True
        sig = self._last_sig
        return (sig is not None and sig.headroom is not None
                and sig.headroom > self.resume_margin)

    # --- admission order: deficit round robin over tenants ---------------
    def _cost(self, req):
        # total sequence footprint in tokens — the same unit the pool
        # reserves and predicted_service_seconds prices
        return float(req.prompt.size + req.max_new_tokens)

    def pick_index(self, engine):
        """Index into engine.queue of the next request to admit, or None
        to admit nothing this step. Priority classes strictly dominate;
        within a class, tenants are served deficit-round-robin (each
        ring visit earns `quantum` tokens of credit; serving a request
        spends its footprint), so one tenant's flood of long prompts
        cannot starve another's short ones. Tenants at their lane quota
        are skipped and the deferral counted. The walk is bounded and
        falls back to the class's first queued request, so admission
        always makes progress."""
        queue = engine.queue
        if not queue:
            return None
        if self.fifo:
            return 0
        lanes_per_tenant: dict[str, int] = {}
        lanes_per_adapter: dict[str, int] = {}
        for r in engine.lanes:
            if r is not None:
                lanes_per_tenant[r.tenant] = \
                    lanes_per_tenant.get(r.tenant, 0) + 1
                if r.adapter:
                    lanes_per_adapter[r.adapter] = \
                        lanes_per_adapter.get(r.adapter, 0) + 1
        for _, (req, _ln, _tok) in engine._preempted.items():
            lanes_per_tenant[req.tenant] = \
                lanes_per_tenant.get(req.tenant, 0) + 1
            if req.adapter:
                lanes_per_adapter[req.adapter] = \
                    lanes_per_adapter.get(req.adapter, 0) + 1
        deferred: set[str] = set()
        deferred_ad: set[str] = set()
        for cls in PRIORITY_CLASSES:
            heads: dict[str, int] = {}     # tenant -> queue index of head
            for i, r in enumerate(queue):
                if r.priority != cls or r.tenant in heads:
                    continue
                if (self.tenant_quota is not None
                        and lanes_per_tenant.get(r.tenant, 0)
                        >= self.tenant_quota):
                    if r.tenant not in deferred:
                        deferred.add(r.tenant)
                        _metric("serving_quota_deferrals_total",
                                tenant=r.tenant).inc()
                    continue
                if (self.adapter_quota is not None and r.adapter
                        and lanes_per_adapter.get(r.adapter, 0)
                        >= self.adapter_quota):
                    if r.adapter not in deferred_ad:
                        deferred_ad.add(r.adapter)
                        _metric("serving_adapter_quota_deferrals_total",
                                adapter=r.adapter).inc()
                    continue
                heads[r.tenant] = i
            if not heads:
                continue
            ring = self._rings.setdefault(cls, [])
            for t in heads:
                if t not in ring:
                    ring.append(t)
            # a tenant with nothing queued in this class forfeits its
            # residual credit (classic DRR: deficit resets on empty)
            for key in [k for k in self._deficit
                        if k[0] == cls and k[1] not in heads]:
                del self._deficit[key]
            n = len(ring)
            max_cost = max(self._cost(queue[i]) for i in heads.values())
            budget = n * (int(max_cost // self.quantum) + 2)
            cur = self._cursors.get(cls, 0)
            for _ in range(budget):
                t = ring[cur % n]
                cur += 1
                if t not in heads:
                    continue
                cost = self._cost(queue[heads[t]])
                credit = self._deficit.get((cls, t), 0.0) + self.quantum
                if credit >= cost:
                    self._deficit[(cls, t)] = credit - cost
                    self._cursors[cls] = cur
                    return heads[t]
                self._deficit[(cls, t)] = credit
            self._cursors[cls] = cur
            # bounded-walk fallback: guaranteed progress for the class
            return min(heads.values())
        return None

    # --- failure contract ------------------------------------------------
    def _degrade_fifo(self, engine, why="fault"):
        """serve.sched_decide degradation: this scheduler becomes a
        counted no-op for the engine's lifetime. Brownout knobs are
        restored to base, best_effort shedding stops, and parked lanes
        resume (should_resume is unconditionally True once degraded) —
        the engine falls back to exactly its pre-scheduler FIFO
        behavior, it never deadlocks on a broken policy."""
        if self.fifo:
            return
        self.fifo = True
        if self.level != 0:
            self._transition(0, "down")
        self._apply(engine)
        self.shed_best_effort = False
        _metric("serving_runtime_degradations_total",
                what="sched_fifo").inc()
        if self._rec.enabled:
            self._rec.record("degrade", what="sched_fifo", why=why)
