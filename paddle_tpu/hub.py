"""paddle.hub. reference: python/paddle/hapi/hub.py (list, help, load with
github/gitee/local sources).

Zero-egress environment: only source='local' works (a directory containing
hubconf.py); remote sources raise with a clear message instead of hanging.
"""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _check_source(source):
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network egress; this environment "
            "is offline — use source='local' with a directory containing "
            "hubconf.py")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoints exposed by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"{model} not found in {repo_dir}/{_HUBCONF}")
    return fn.__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"{model} not found in {repo_dir}/{_HUBCONF}")
    return fn(**kwargs)
