"""paddle.sysconfig. reference: python/paddle/sysconfig.py
(get_include, get_lib)."""

from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory of C headers for building custom ops (the C ABI contract
    lives in utils/cpp_extension.py docstrings; native sources in /native)."""
    return os.path.join(os.path.dirname(_ROOT), "native")


def get_lib():
    """Directory of built native libraries."""
    return os.path.join(_ROOT, "_native")
