"""Discrete Fourier transforms. reference: python/paddle/fft.py.

TPU-native: every transform is jnp.fft lowered by XLA (TPU FFT runs as
composed matmuls/transposes on the MXU for small sizes, or the XLA FFT HLO);
autograd comes from jax.vjp through framework.core.execute — no hand-written
fft_grad kernels (reference: paddle/phi/kernels/funcs/cufft_util.h,
paddle/phi/kernels/gpu/fft_kernel.cu).
"""

from __future__ import annotations

import jax.numpy as jnp

from .framework.core import execute

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "hfft2", "ihfft2", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _check_norm(norm):
    if norm is None:
        return "backward"
    if norm not in _NORMS:
        raise ValueError(f"norm must be one of {_NORMS}, got {norm!r}")
    return norm


def _1d(jnp_fn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        norm = _check_norm(norm)
        return execute(lambda a: jnp_fn(a, n=n, axis=axis, norm=norm), x,
                       _name=jnp_fn.__name__)
    return op


def _2d(jnp_fn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        norm = _check_norm(norm)
        return execute(lambda a: jnp_fn(a, s=s, axes=axes, norm=norm), x,
                       _name=jnp_fn.__name__)
    return op


def _nd(jnp_fn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        norm = _check_norm(norm)
        return execute(lambda a: jnp_fn(a, s=s, axes=axes, norm=norm), x,
                       _name=jnp_fn.__name__)
    return op


fft = _1d(jnp.fft.fft)
ifft = _1d(jnp.fft.ifft)
rfft = _1d(jnp.fft.rfft)
irfft = _1d(jnp.fft.irfft)
hfft = _1d(jnp.fft.hfft)
ihfft = _1d(jnp.fft.ihfft)

fft2 = _2d(jnp.fft.fft2)
ifft2 = _2d(jnp.fft.ifft2)
fftn = _nd(jnp.fft.fftn)
ifftn = _nd(jnp.fft.ifftn)
rfft2 = _2d(jnp.fft.rfft2)
irfft2 = _2d(jnp.fft.irfft2)
rfftn = _nd(jnp.fft.rfftn)
irfftn = _nd(jnp.fft.irfftn)


def _h2(fwd, axes_default=(-2, -1)):
    def op(x, s=None, axes=axes_default, norm="backward", name=None):
        norm = _check_norm(norm)

        def f(a):
            # hfft2/hfftn = real-output transform of hermitian input: c2c along
            # the leading axes then hfft last. The inverse must mirror in
            # reverse order — ihfft (real input) first, then ifft on the rest.
            out = a
            ax = list(axes) if axes is not None else list(range(a.ndim))
            if fwd:
                for i, axis in enumerate(ax[:-1]):
                    nn = None if s is None else s[i]
                    out = jnp.fft.fft(out, n=nn, axis=axis, norm=norm)
                nn = None if s is None else s[-1]
                out = jnp.fft.hfft(out, n=nn, axis=ax[-1], norm=norm)
            else:
                nn = None if s is None else s[-1]
                out = jnp.fft.ihfft(out, n=nn, axis=ax[-1], norm=norm)
                for i, axis in enumerate(ax[:-1]):
                    nn = None if s is None else s[i]
                    out = jnp.fft.ifft(out, n=nn, axis=axis, norm=norm)
            return out
        return execute(f, x, _name="hfft2" if fwd else "ihfft2")
    return op


hfft2 = _h2(True)
ihfft2 = _h2(False)
hfftn = _h2(True, axes_default=None)
ihfftn = _h2(False, axes_default=None)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.core import Tensor
    out = jnp.fft.fftfreq(n, d=d)
    if dtype is not None:
        from .framework import dtypes as _dt
        out = out.astype(_dt.convert_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.core import Tensor
    out = jnp.fft.rfftfreq(n, d=d)
    if dtype is not None:
        from .framework import dtypes as _dt
        out = out.astype(_dt.convert_dtype(dtype))
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    return execute(lambda a: jnp.fft.fftshift(a, axes=axes), x, _name="fftshift")


def ifftshift(x, axes=None, name=None):
    return execute(lambda a: jnp.fft.ifftshift(a, axes=axes), x, _name="ifftshift")
