"""Quant/dequant format layers — the QAT->deployment conversion pieces.

reference capability: python/paddle/nn/quant/format.py (LinearQuanter /
LinearDequanter / LinearQuanterDequanter, fake_fp8_quant/dequant). The
reference routes integer formats through the quantize_linear C++ op and
fp8 through clip-then-cast; here every format is a few jnp ops XLA fuses,
and the fp8 path rounds through REAL ml_dtypes float8 storage types
(jnp.float8_e4m3fn / jnp.float8_e5m2), so the fake-quant error matches
what serialized fp8 weights will actually reproduce.

quant_bits contract (matches the reference):
  int    -> SYMMETRIC integer grid, clip to [-qmax, qmax] with
            qmax = 2^(b-1)-1. (The reference's deployed op admits -qmax-1;
            we drop that one level so conversion is bit-exact with this
            framework's QAT fake-quant, which trains on [-qmax, qmax].)
  (4, 3) -> float8 e4m3 (finite range +-448)
  (5, 2) -> float8 e5m2 (finite range +-57344)

Checkpoint interop caveat (ADVICE r5 #3 — the asymmetric qmin level):
tensors serialized by the reference's quantize_linear may CONTAIN the
qmin = -qmax-1 level (e.g. -128 at 8 bits). Both directions are handled,
but only one is lossless:
  - LinearDequanter ACCEPTS qmin levels exactly — dequantization is
    linear ((x - zp) * s / qmax), so a -qmax-1 level reconstructs to
    -(qmax+1)/qmax * s with no clipping. Reference-written checkpoints
    load losslessly.
  - LinearQuanter EMITS only the symmetric grid: re-quantizing a value
    that reconstructs the reference's qmin level clamps it one level up,
    to -qmax (a 1-ulp-of-grid shift on those entries, ~0.8% of scale at
    8 bits). This is deliberate — emitting -qmax-1 would break bit-exact
    round-trips with this framework's own QAT observers, which train on
    the symmetric grid. Round-tripping a reference checkpoint through
    quant->dequant here is therefore NOT the identity on qmin entries;
    pure dequantization (deployment inference) is.

Channels whose scale is 0 (never-observed quanters) pass through
UNQUANTIZED — the same guard the QAT fake-quant applies — instead of
collapsing to zeros through a divide-by-zero.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...framework.core import Tensor, execute
from ..layer.layers import Layer

__all__ = ["LinearQuanter", "LinearDequanter", "LinearQuanterDequanter",
           "fake_fp8_quant", "fake_fp8_dequant", "fp8_limits"]

_FP8 = {
    "e4m3": (448.0, "float8_e4m3fn"),
    "e5m2": (57344.0, "float8_e5m2"),
}


def fp8_limits(type="e4m3"):
    """(finite_max, storage dtype name) of an fp8 format — THE grid
    constants every fp8 consumer in the framework scales against (the
    fake-quant layers here and the quantized paged-KV block format in
    ops/paged_attention share them, so serialized fp8 tensors and
    KV blocks reproduce the same values)."""
    if type not in _FP8:
        raise NotImplementedError("only e4m3 / e5m2 fp8 formats exist")
    return _FP8[type]


def _axis_shape(scale, ndim, axis):
    if axis is None or axis < 0 or scale.ndim == 0:
        return scale
    shape = [1] * ndim
    shape[axis] = scale.size
    return scale.reshape(shape)


def fake_fp8_quant(x, scale, axis=-1, type="e4m3"):
    """Scale into the fp8 grid, round through the REAL fp8 dtype, return
    in the input dtype (still scaled — pair with fake_fp8_dequant).
    Zero-scale entries pass through unquantized."""
    if type not in _FP8:
        raise NotImplementedError("only e4m3 / e5m2 fp8 formats exist")
    fmax, fp8_dtype = _FP8[type]

    def f(a, s):
        if s.ndim > 1:
            raise NotImplementedError(
                "fp8 formats support tensor-wise or per-channel scales; "
                "group-wise (2-D) scales are int-format only")
        s = _axis_shape(s, a.ndim, axis)
        a32 = a.astype(jnp.float32)
        safe = jnp.where(s > 0, s, 1.0)
        scaled = jnp.clip(a32 * fmax / safe, -fmax, fmax)
        q = scaled.astype(fp8_dtype).astype(jnp.float32)
        return jnp.where(s > 0, q, a32).astype(a.dtype)

    return execute(f, x, scale, _name="fake_fp8_quant")


def fake_fp8_dequant(x, scale, axis=-1, type="e4m3"):
    if type not in _FP8:
        raise NotImplementedError("only e4m3 / e5m2 fp8 formats exist")
    fmax, _ = _FP8[type]

    def f(a, s):
        if s.ndim > 1:
            raise NotImplementedError(
                "fp8 formats support tensor-wise or per-channel scales; "
                "group-wise (2-D) scales are int-format only")
        s = _axis_shape(s, a.ndim, axis)
        a32 = a.astype(jnp.float32)
        return jnp.where(s > 0, a32 / fmax * s, a32).astype(a.dtype)

    return execute(f, x, scale, _name="fake_fp8_dequant")


def _parse_bits(bit_length):
    """-> (qmax, fp8_type_or_None). Integer grids are symmetric."""
    if isinstance(bit_length, (tuple, list)):
        if tuple(bit_length) == (4, 3):
            return 448.0, "e4m3"
        if tuple(bit_length) == (5, 2):
            return 57344.0, "e5m2"
        raise NotImplementedError(
            "only float8 formats (4,3)=e4m3 and (5,2)=e5m2 are supported "
            "as tuple quant_bits")
    return float((1 << (int(bit_length) - 1)) - 1), None


class _ScaledFormat(Layer):
    """Shared scale/zero-point normalization for the format layers."""

    def __init__(self, scales, zero_point, quant_axis, bit_length,
                 group_size):
        super().__init__()
        self._scales = jnp.asarray(
            scales._data if isinstance(scales, Tensor) else scales,
            jnp.float32)
        self._zero_point = (jnp.asarray(
            zero_point._data if isinstance(zero_point, Tensor)
            else zero_point, jnp.float32) if zero_point is not None
            else jnp.zeros((), jnp.float32))
        self._quant_axis = -1 if quant_axis is None else quant_axis
        self._qmax, self._fp8 = _parse_bits(bit_length)
        self._group_size = group_size
        if self._fp8 is not None and zero_point is not None and \
                bool(jnp.any(self._zero_point != 0)):
            raise NotImplementedError(
                "fp8 formats are symmetric; zero_point must be 0/None")

    def _prep(self, a):
        """-> (scale, zero_point) broadcastable against `a`, honoring
        quant_axis (1-D scales) or row-group layout (2-D scales)."""
        s, z = self._scales, self._zero_point
        if s.ndim > 1:   # group-wise: one scale row per `group` input rows
            s = jnp.repeat(s, self._group_size, 0)[:a.shape[0]]
            if z.ndim > 1:
                z = jnp.repeat(z, self._group_size, 0)[:a.shape[0]]
            return s, z
        return (_axis_shape(s, a.ndim, self._quant_axis),
                _axis_shape(z, a.ndim, self._quant_axis))


class LinearQuanter(_ScaledFormat):
    """x -> quantized grid (int levels or fp8), kept in x's dtype.

    Integer output is SYMMETRIC: levels in [-qmax, qmax]. Inputs that
    land on the reference's asymmetric qmin level (-qmax-1) are accepted
    and clamp to -qmax — see the module docstring's interop caveat."""

    def __init__(self, scales, zero_point=None, quant_axis=None,
                 bit_length=8, group_size=128):
        super().__init__(scales, zero_point, quant_axis, bit_length,
                         group_size)

    def forward(self, x):
        if self._fp8 is not None:
            return fake_fp8_quant(x, Tensor(self._scales),
                                  self._quant_axis, self._fp8)
        qmax = self._qmax

        def f(a):
            s, z = self._prep(a)
            a32 = a.astype(jnp.float32)
            safe = jnp.where(s > 0, s, 1.0)
            q = jnp.clip(jnp.round(a32 / safe * qmax) + z, -qmax, qmax)
            return jnp.where(s > 0, q, a32).astype(a.dtype)

        return execute(f, x, _name="quantize_linear")

    @staticmethod
    def from_quanter(quanter):
        return LinearQuanter(quanter.scales(), quant_axis=None,
                             bit_length=quanter.bit_length())


class LinearDequanter(_ScaledFormat):
    """Inverse of LinearQuanter (same scale/axis/bits contract).

    Accepts the reference's full asymmetric level range on input: the
    map is linear and unclipped, so a qmin = -qmax-1 level written by
    the reference's quantize_linear reconstructs exactly (module
    docstring, interop caveat)."""

    def __init__(self, scales, zero_point=None, quant_axis=None,
                 bit_length=8, group_size=128):
        super().__init__(scales, zero_point, quant_axis, bit_length,
                         group_size)

    def forward(self, x):
        if self._fp8 is not None:
            return fake_fp8_dequant(x, Tensor(self._scales),
                                    self._quant_axis, self._fp8)
        qmax = self._qmax

        def f(a):
            s, z = self._prep(a)
            a32 = a.astype(jnp.float32)
            return jnp.where(s > 0, (a32 - z) * s / qmax,
                             a32).astype(a.dtype)

        return execute(f, x, _name="dequantize_linear")

    @staticmethod
    def from_quanter(quanter):
        return LinearDequanter(quanter.scales(), quant_axis=None,
                               bit_length=quanter.bit_length())


class LinearQuanterDequanter(Layer):
    """Quant->dequant pair — the deployed form of a trained fake-quanter
    (reference: LinearQuanterDequanter.from_quanter, the QAT->inference
    conversion target)."""

    def __init__(self, quanter, dequanter):
        super().__init__()
        self._quanter = quanter
        self._dequanter = dequanter

    def forward(self, x):
        out = x
        if self._quanter is not None:
            out = self._quanter(out)
        if self._dequanter is not None:
            out = self._dequanter(out)
        return out

    @staticmethod
    def from_quanter(quanter):
        return LinearQuanterDequanter(LinearQuanter.from_quanter(quanter),
                                      LinearDequanter.from_quanter(quanter))
