"""paddle.nn.quant — weight-only / LLM.int8 quantized linear surface.

reference: python/paddle/nn/quant/__init__.py (Stub, weight_quantize,
weight_dequantize, weight_only_linear, llm_int8_linear; kernels
weight_quantize/weight_only_linear in ops.yaml).

TPU-native design: the reference's CUDA kernels exist to feed tensor-core
int8/int4 GEMMs with hand-packed layouts (and gate on SM arch). On TPU the
MXU consumes int8 natively and XLA fuses the dequant multiply into the
matmul epilogue, so the ops are expressed as plain jnp: per-channel (or
group-wise) absmax quantization, int8 matmul with int32 accumulation,
scale epilogue. int4 is stored as int8 values in [-8, 7] — nibble packing
is a GPU memory-layout artifact; XLA's i4 support handles packing when it
lowers. The `arch` parameter is accepted and ignored (no SM arches here).

Layouts match the reference contract: weight_quantize takes x of shape
(k, n) and returns (quantized weight of shape (n, k) — the transposition —
and per-out-channel scale of shape (n,); group_size>0 gives scale
(n, k//group_size)).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import execute
from ...framework import dtypes as _dt
from ..layer.layers import Layer

__all__ = ["Stub", "weight_only_linear", "llm_int8_linear",
           "weight_quantize", "weight_dequantize"]

_ALGOS = ("weight_only_int8", "weight_only_int4", "llm.int8")


def _check(algo, group_size):
    if algo not in _ALGOS:
        raise ValueError(f"algo must be one of {_ALGOS}, got {algo!r}")
    if group_size not in (-1, 64, 128):
        raise ValueError(f"group_size must be -1/64/128, got {group_size}")


def _qmax(algo):
    return 7.0 if algo == "weight_only_int4" else 127.0


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """(k, n) float weight -> ((n, k) int8 weight, scale). reference:
    nn/quant/quantized_linear.py:56."""
    _check(algo, group_size)
    qmax = _qmax(algo)

    def f(w):
        wt = w.astype(jnp.float32).T  # (n, k)
        if group_size == -1:
            absmax = jnp.max(jnp.abs(wt), axis=1)  # (n,)
            scale = absmax / qmax
            q = jnp.round(wt / jnp.maximum(scale, 1e-10)[:, None])
        else:
            n, k = wt.shape
            if k % group_size:
                raise ValueError(
                    f"in-features {k} not divisible by group_size "
                    f"{group_size}")
            g = wt.reshape(n, k // group_size, group_size)
            absmax = jnp.max(jnp.abs(g), axis=2)  # (n, k/gs)
            scale = absmax / qmax
            q = jnp.round(g / jnp.maximum(scale, 1e-10)[:, :, None])
            q = q.reshape(n, k)
        q = jnp.clip(q, -qmax - 1, qmax).astype(jnp.int8)
        return q, scale.astype(jnp.float32)

    return execute(f, x, _name="weight_quantize")


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float16",
                      group_size=-1):
    """(n, k) int8 weight + scale -> (k, n) float weight. reference:
    nn/quant/quantized_linear.py:123."""
    _check(algo, group_size)
    dt = _dt.convert_dtype(out_dtype)

    def f(q, s):
        qf = q.astype(jnp.float32)
        if group_size == -1:
            w = qf * s[:, None]
        else:
            n, k = qf.shape
            g = qf.reshape(n, k // group_size, group_size)
            w = (g * s[:, :, None]).reshape(n, k)
        return w.T.astype(dt)

    return execute(f, x, scale, _name="weight_dequantize")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """x @ dequant(weight).T + bias with the dequant fused by XLA into the
    matmul. weight: (n, k) int8 from weight_quantize. reference:
    nn/quant/quantized_linear.py:183."""
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(f"weight_dtype must be int8/int4, got {weight_dtype}")
    if group_size not in (-1, 64, 128):
        raise ValueError(f"group_size must be -1/64/128, got {group_size}")

    def f(a, q, *rest):
        it = iter(rest)
        s = next(it) if weight_scale is not None else None
        b = next(it) if bias is not None else None
        qf = q.astype(a.dtype)
        if s is not None:
            if group_size == -1:
                wf = qf * s.astype(a.dtype)[:, None]          # (n, k)
            else:
                n, k = qf.shape
                g = qf.reshape(n, k // group_size, group_size)
                wf = (g * s.astype(a.dtype)[:, :, None]).reshape(n, k)
        else:
            wf = qf
        out = a @ wf.T
        if b is not None:
            out = out + b
        return out

    args = (x, weight)
    if weight_scale is not None:
        args += (weight_scale,)
    if bias is not None:
        args += (bias,)
    return execute(f, *args, _name="weight_only_linear")


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """LLM.int8 (Dettmers et al.): per-token int8 activation quantization
    with fp outlier decomposition. Columns of x holding any |value| >
    threshold run against the dequantized weight in x's dtype; the rest run
    int8 x int8 -> int32 with a scale epilogue. weight: (n, k) int8.
    reference: nn/quant/quantized_linear.py:276.
    """

    def f(a, q, *rest):
        it = iter(rest)
        s = next(it) if weight_scale is not None else None
        b = next(it) if bias is not None else None
        af = a.astype(jnp.float32)
        k = af.shape[-1]
        outlier = jnp.any(jnp.abs(af) > threshold, axis=tuple(
            range(af.ndim - 1)))                               # (k,)
        a_in = jnp.where(outlier, 0.0, af)
        # per-token absmax int8 quantization of the inlier block
        tok_max = jnp.max(jnp.abs(a_in), axis=-1, keepdims=True)
        a_scale = jnp.maximum(tok_max, 1e-10) / 127.0
        aq = jnp.clip(jnp.round(a_in / a_scale), -128, 127).astype(jnp.int8)
        acc = jax.lax.dot_general(
            aq, q, dimension_numbers=(((aq.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32).astype(jnp.float32)
        w_scale = (s.astype(jnp.float32) if s is not None
                   else jnp.ones((q.shape[0],), jnp.float32))
        out = acc * a_scale * w_scale                          # (..., n)
        # outlier columns in full precision against dequantized weight
        a_out = jnp.where(outlier, af, 0.0)
        wf = q.astype(jnp.float32) * w_scale[:, None]          # (n, k)
        out = out + a_out @ wf.T
        if b is not None:
            out = out + b.astype(jnp.float32)
        return out.astype(a.dtype)

    args = (x, weight)
    if weight_scale is not None:
        args += (weight_scale,)
    if bias is not None:
        args += (bias,)
    return execute(f, *args, _name="llm_int8_linear")


class Stub(Layer):
    """Observer placeholder inserted where a quanter should attach.
    reference: python/paddle/nn/quant/stub.py — resolved to a real quanter
    by quantization.QAT.quantize from the model's config."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return x


from . import format  # noqa: E402  (QAT->deployment conversion layers)
from .format import (  # noqa: E402,F401
    LinearDequanter, LinearQuanter, LinearQuanterDequanter,
    fake_fp8_dequant, fake_fp8_quant)
