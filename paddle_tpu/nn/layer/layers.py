"""nn.Layer base class. reference: python/paddle/nn/layer/layers.py.

Holds Parameters (Tensors with stop_gradient=False) in registries so the
imperative API works eagerly while jit.to_static can lift the same layer into
a pure function (params/buffers become traced inputs/outputs) for XLA.
"""

from __future__ import annotations

import collections
from typing import Callable, Iterator

import jax.numpy as jnp
import numpy as np

from ...framework import dtypes as _dt
from ...framework.core import Parameter, Tensor

__all__ = ["Layer", "LayerList", "ParameterList", "Sequential", "LayerDict"]


class HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._name_scope = name_scope or type(self).__name__.lower()
        self._casted_dtype = None

    # -- construction -------------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """reference: python/paddle/nn/layer/layers.py:create_parameter —
        default init: XavierUniform-ish for weights, zeros for bias (matches
        LayerHelper defaults)."""
        from .. import initializer as I

        dtype = _dt.convert_dtype(dtype or self._dtype)
        init = default_initializer
        trainable = True
        name = None
        lr = 1.0
        if attr is False:
            return None
        if attr is not None and not isinstance(attr, (bool,)):
            init = getattr(attr, "initializer", None) or init
            trainable = getattr(attr, "trainable", True)
            name = getattr(attr, "name", None)
            lr = getattr(attr, "learning_rate", 1.0)
        if init is None:
            # set_global_initializer overrides the built-in defaults for
            # params whose ParamAttr carries no explicit initializer
            # (reference: nn/initializer/set_global_initializer)
            init = I._global_bias_init if is_bias else I._global_weight_init
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        data = init._init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, name=name, trainable=trainable)
        p.optimize_attr = {"learning_rate": lr}
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if tensor is not None:
            tensor.persistable = persistable
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute magic ----------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() first")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() first")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        elif buffers is not None and name in buffers:
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for key in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(key)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} has no attribute {name!r}")

    def __delattr__(self, name):
        for key in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(key)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # -- traversal ----------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (name + "." + pname if name else pname), p
            if not include_sublayers:
                break

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = prefix + "." + name if prefix else name
            yield from layer.named_sublayers(prefix=sub_prefix, include_self=True,
                                             layers_set=layers_set)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return [l for l in self._sub_layers.values() if l is not None]

    def named_children(self):
        return [(n, l) for n, l in self._sub_layers.items() if l is not None]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (name + "." + bname if name else bname), b

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    # -- mode ---------------------------------------------------------------
    def train(self):
        for layer in self.named_sublayers(include_self=True):
            layer[1].training = True
        return self

    def eval(self):
        for layer in self.named_sublayers(include_self=True):
            layer[1].training = False
        return self

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = _dt.convert_dtype(dtype)
            for _, p in self.named_parameters():
                if jnp.issubdtype(p._data.dtype, jnp.floating):
                    p._data = p._data.astype(dt)
            for _, b in self.named_buffers():
                if b is not None and jnp.issubdtype(b._data.dtype, jnp.floating):
                    b._data = b._data.astype(dt)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix.rstrip(".")):
            if name.split(".")[-1] not in self._non_persistable_buffer_names:
                dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                v = state_dict[name]
                arr = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                t._data = arr.astype(t._data.dtype).reshape(t._data.shape)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- hooks + call -------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._id] = hook
        return helper

    def register_forward_post_hook(self, hook):
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._id] = hook
        return helper

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = type(self).__name__ + "(" + extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


class Sequential(Layer):
    """reference: python/paddle/nn/layer/container.py:Sequential."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers.keys())
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, input):
        for layer in self._sub_layers.values():
            input = layer(input)
        return input


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, layer in enumerate(sublayers):
                self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return self._sub_layers[str(idx if idx >= 0 else len(self) + idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, (dict, collections.OrderedDict)) else sublayers
        for k, v in items:
            self[k] = v

    def pop(self, key):
        layer = self._sub_layers[key]
        del self._sub_layers[key]
        return layer

    def clear(self):
        self._sub_layers.clear()


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self
