"""Long-tail nn layers closing the reference surface.

reference: python/paddle/nn/layer/ — common.py (ZeroPad1D/3D, Unflatten),
activation.py (Softmax2D), distance.py (PairwiseDistance), pooling.py
(MaxUnPool*, FractionalMaxPool*), loss.py (MultiMarginLoss, HSigmoidLoss),
container.py (ParameterDict).
"""

from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = [
    "ZeroPad1D", "ZeroPad3D", "Unflatten", "Softmax2D", "PairwiseDistance",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D", "FractionalMaxPool2D",
    "FractionalMaxPool3D", "MultiMarginLoss", "HSigmoidLoss",
    "FeatureAlphaDropout", "ParameterDict", "RNNTLoss",
    "AdaptiveLogSoftmaxWithLoss",
]


class ZeroPad1D(Layer):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, "constant", 0.0, self.data_format)


class ZeroPad3D(Layer):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, "constant", 0.0, self.data_format)


class Unflatten(Layer):
    """reference: nn/layer/common.py Unflatten — expand one dim into shape."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = list(shape)

    def forward(self, x):
        from ...tensor.manipulation import unflatten
        return unflatten(x, self.axis, self.shape)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW. reference: activation.py."""

    def forward(self, x):
        assert x.ndim in (3, 4), "Softmax2D expects CHW or NCHW"
        return F.softmax(x, axis=-3)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class _MaxUnPoolN(Layer):
    _fn = None
    _ndim = 2

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        fn = getattr(F, f"max_unpool{self._ndim}d")
        return fn(x, indices, self.kernel_size, self.stride, self.padding,
                  output_size=self.output_size)


class MaxUnPool1D(_MaxUnPoolN):
    _ndim = 1


class MaxUnPool2D(_MaxUnPoolN):
    _ndim = 2


class MaxUnPool3D(_MaxUnPoolN):
    _ndim = 3


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size,
                                       random_u=self.random_u,
                                       return_mask=self.return_mask)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size,
                                       random_u=self.random_u,
                                       return_mask=self.return_mask)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p = p
        self.margin = margin
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class HSigmoidLoss(Layer):
    """reference: nn/layer/loss.py HSigmoidLoss (owns the tree weights)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError(
                "HSigmoidLoss: custom trees are not supported (default "
                "complete binary tree only)")
        self.num_classes = num_classes
        import jax
        import jax.numpy as jnp
        from ...framework.core import Parameter
        from ...framework.random import next_key
        scale = feature_size ** -0.5
        self.weight = Parameter(jax.random.normal(
            next_key(), (num_classes, feature_size), jnp.float32) * scale)
        self.bias = None
        if bias_attr is not False:
            self.bias = Parameter(jnp.zeros((num_classes,), jnp.float32))

    def forward(self, input, label):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, training=self.training)


class ParameterDict(Layer):
    """reference: nn/layer/container.py ParameterDict."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            self.update(parameters)

    def __getitem__(self, key):
        return self._parameters[key]

    def __setitem__(self, key, parameter):
        self.add_parameter(key, parameter)

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters)

    def __contains__(self, key):
        return key in self._parameters

    def keys(self):
        return self._parameters.keys()

    def values(self):
        return self._parameters.values()

    def items(self):
        return self._parameters.items()

    def update(self, parameters):
        items = parameters.items() if hasattr(parameters, "items") \
            else parameters
        for k, v in items:
            self.add_parameter(k, v)
        return self


class RNNTLoss(Layer):
    """reference: nn/layer/loss.py RNNTLoss (warprnnt)."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax head (Grave et al. 2017).
    reference: nn/layer/activation.py AdaptiveLogSoftmaxWithLoss — owns the
    head weight and per-cluster down-projection + class weights."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        import jax
        import jax.numpy as jnp
        from ...framework.core import Parameter
        from ...framework.random import next_key
        cutoffs = list(cutoffs)
        if not cutoffs or cutoffs != sorted(set(cutoffs)) \
                or cutoffs[-1] > n_classes - 1:
            raise ValueError(f"invalid cutoffs {cutoffs} for "
                             f"n_classes={n_classes}")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        n_clusters = len(cutoffs)
        head_size = cutoffs[0] + n_clusters
        s = in_features ** -0.5
        self.head_weight = Parameter(jax.random.normal(
            next_key(), (in_features, head_size), jnp.float32) * s)
        self.head_bias = Parameter(jnp.zeros((head_size,), jnp.float32)) \
            if head_bias else None
        self.tail_weights = []
        for i in range(n_clusters):
            hsz = max(1, int(in_features // (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            proj = Parameter(jax.random.normal(
                next_key(), (in_features, hsz), jnp.float32) * s)
            cls_w = Parameter(jax.random.normal(
                next_key(), (hsz, osz), jnp.float32) * hsz ** -0.5)
            self.add_parameter(f"tail_proj_{i}", proj)
            self.add_parameter(f"tail_cls_{i}", cls_w)
            self.tail_weights.append((proj, cls_w))

    def forward(self, input, label):
        out, loss = F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs[:-1], head_bias=self.head_bias)
        return out, loss

    def log_prob(self, input):
        """Full (N, n_classes) log-probabilities."""
        import jax
        import jax.numpy as jnp
        from ...framework.core import execute as _ex
        tails = self.tail_weights
        hb = self.head_bias
        c0 = self.cutoffs[0]

        def f(a, hw, *rest):
            logits = a @ hw
            if hb is not None:
                logits = logits + rest[-1]
            head_lp = jax.nn.log_softmax(logits, -1)
            pieces = [head_lp[:, :c0]]
            for i in range(len(tails)):
                proj, cls_w = rest[2 * i], rest[2 * i + 1]
                tail_lp = jax.nn.log_softmax((a @ proj) @ cls_w, -1)
                pieces.append(head_lp[:, c0 + i:c0 + i + 1] + tail_lp)
            return jnp.concatenate(pieces, -1)

        args = [input, self.head_weight] + [w for pair in tails
                                            for w in pair]
        if hb is not None:
            args.append(hb)
        return _ex(f, *args, _name="adaptive_log_softmax")
