"""Norm layers. reference: python/paddle/nn/layer/norm.py."""

from __future__ import annotations

import jax.numpy as jnp

from ...framework.core import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm",
           "SpectralNorm", "RMSNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = "NCHW" if data_format in ("NC", "NCL", "NCHW", "NCDHW") else "NHWC"
        self._use_global_stats = use_global_stats
        self.weight = (self.create_parameter((num_features,), attr=weight_attr,
                                             default_initializer=I.Constant(1.0))
                       if weight_attr is not False else None)
        self.bias = (self.create_parameter((num_features,), attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), jnp.float32)))

    def forward(self, input):
        return F.batch_norm(input, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. On TPU under GSPMD, batch stats are computed over
    the global batch automatically when the batch axis is sharded (XLA
    inserts the all-reduce) — so this is BatchNorm with a doc contract.
    reference: python/paddle/nn/layer/norm.py:SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, data_format=layer._data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._buffers = layer._buffers
            return new
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        self.weight = (self.create_parameter(self._normalized_shape, attr=weight_attr,
                                             default_initializer=I.Constant(1.0))
                       if weight_attr is not False else None)
        self.bias = (self.create_parameter(self._normalized_shape, attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)


class RMSNorm(Layer):
    """Llama-family RMSNorm; fused path in incubate.nn.functional.fused_rms_norm."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter((hidden_size,), attr=weight_attr,
                                            default_initializer=I.Constant(1.0))

    def forward(self, input):
        return F.rms_norm(input, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (self.create_parameter((num_channels,), attr=weight_attr,
                                             default_initializer=I.Constant(1.0))
                       if weight_attr is not False else None)
        self.bias = (self.create_parameter((num_channels,), attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon,
                            self.weight, self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = (self.create_parameter((num_features,), attr=weight_attr,
                                             default_initializer=I.Constant(1.0))
                       if weight_attr is not False else None)
        self.bias = (self.create_parameter((num_features,), attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, input):
        return F.instance_norm(input, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, input):
        return F.local_response_norm(input, *self.args)


class SpectralNorm(Layer):
    """Power-iteration spectral norm. reference: python/paddle/nn/layer/norm.py:SpectralNorm."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter((h,), default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter((w,), default_initializer=I.Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...framework.core import execute
        import jax
        dim = self._dim
        eps = self._epsilon
        iters = self._power_iters
        u0, v0 = self.weight_u._data, self.weight_v._data

        def f(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            u = jax.lax.stop_gradient(u)
            v = jax.lax.stop_gradient(v)
            sigma = u @ wm @ v
            return w / sigma

        out = execute(f, weight, _name="spectral_norm")
        return out
