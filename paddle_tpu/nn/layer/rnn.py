"""Recurrent layers via lax.scan (compiler-friendly TPU control flow).

reference: python/paddle/nn/layer/rnn.py; CUDA kernels
paddle/phi/kernels/gpu/rnn_kernel.cu (cuDNN). Here each layer is one
lax.scan whose body is a fused cell matmul — XLA pipelines the scan on TPU.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, execute
from .. import initializer as I
from .layers import Layer

__all__ = ["SimpleRNN", "LSTM", "GRU", "SimpleRNNCell", "LSTMCell", "GRUCell",
           "RNN", "BiRNN", "RNNCellBase"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        hs = self.hidden_size
        if getattr(self, "_is_lstm", False):
            return (Tensor(jnp.full((batch, hs), init_value, jnp.float32)),
                    Tensor(jnp.full((batch, hs), init_value, jnp.float32)))
        return Tensor(jnp.full((batch, hs), init_value, jnp.float32))


def _cell_params(layer, input_size, hidden_size, gates, suffix=""):
    std = 1.0 / math.sqrt(hidden_size)
    u = I.Uniform(-std, std)
    wi = layer.create_parameter((gates * hidden_size, input_size), default_initializer=u)
    wh = layer.create_parameter((gates * hidden_size, hidden_size), default_initializer=u)
    bi = layer.create_parameter((gates * hidden_size,), is_bias=True, default_initializer=u)
    bh = layer.create_parameter((gates * hidden_size,), is_bias=True, default_initializer=u)
    layer.add_parameter("weight_ih" + suffix, wi)
    layer.add_parameter("weight_hh" + suffix, wh)
    layer.add_parameter("bias_ih" + suffix, bi)
    layer.add_parameter("bias_hh" + suffix, bh)
    return wi, wh, bi, bh


def _rnn_step(mode, x_t, h, c, wi, wh, bi, bh, activation="tanh"):
    g = x_t @ wi.T + bi + h @ wh.T + bh
    if mode == "rnn":
        return (jnp.tanh(g) if activation == "tanh" else jax.nn.relu(g)), None
    if mode == "gru":
        # paddle GRU: r,z,c gate layout
        hs = h.shape[-1]
        gi = x_t @ wi.T + bi
        gh = h @ wh.T + bh
        r = jax.nn.sigmoid(gi[..., :hs] + gh[..., :hs])
        z = jax.nn.sigmoid(gi[..., hs:2 * hs] + gh[..., hs:2 * hs])
        n = jnp.tanh(gi[..., 2 * hs:] + r * gh[..., 2 * hs:])
        return (1 - z) * n + z * h, None
    # lstm: i,f,g,o
    hs = h.shape[-1]
    i = jax.nn.sigmoid(g[..., :hs])
    f = jax.nn.sigmoid(g[..., hs:2 * hs])
    gg = jnp.tanh(g[..., 2 * hs:3 * hs])
    o = jax.nn.sigmoid(g[..., 3 * hs:])
    c_new = f * c + i * gg
    return o * jnp.tanh(c_new), c_new


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        _cell_params(self, input_size, hidden_size, 1)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        def f(x, h, wi, wh, bi, bh):
            out, _ = _rnn_step("rnn", x, h, None, wi, wh, bi, bh, self.activation)
            return out
        h = execute(f, inputs, states, self.weight_ih, self.weight_hh,
                    self.bias_ih, self.bias_hh, _name="rnn_cell")
        return h, h


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        _cell_params(self, input_size, hidden_size, 3)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        def f(x, h, wi, wh, bi, bh):
            out, _ = _rnn_step("gru", x, h, None, wi, wh, bi, bh)
            return out
        h = execute(f, inputs, states, self.weight_ih, self.weight_hh,
                    self.bias_ih, self.bias_hh, _name="gru_cell")
        return h, h


class LSTMCell(RNNCellBase):
    _is_lstm = True

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        _cell_params(self, input_size, hidden_size, 4)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h0, c0 = states
        def f(x, h, c, wi, wh, bi, bh):
            return _rnn_step("lstm", x, h, c, wi, wh, bi, bh)
        h, c = execute(f, inputs, h0, c0, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh, _name="lstm_cell")
        return h, (h, c)


class RNN(Layer):
    """Wrap a cell into a scan over time. reference: nn/layer/rnn.py:RNN."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        t_axis = 0 if self.time_major else 1
        steps = inputs.shape[t_axis]
        if initial_states is None:
            batch_ref_ax = 1 if self.time_major else 0
            initial_states = self.cell.get_initial_states(
                inputs, batch_dim_idx=batch_ref_ax)
        outs = []
        states = initial_states
        idxs = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        from ...tensor.manipulation import stack
        for t in idxs:
            x_t = inputs[(slice(None),) * t_axis + (t,)]
            out, states = self.cell(x_t, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis=t_axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.fw = RNN(cell_fw, False, time_major)
        self.bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat
        s_fw, s_bw = (initial_states if initial_states is not None else (None, None))
        o1, st1 = self.fw(inputs, s_fw)
        o2, st2 = self.bw(inputs, s_bw)
        return concat([o1, o2], axis=-1), (st1, st2)


class _RNNBase(Layer):
    mode = "rnn"
    gates = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirectional else 1
        self._params = []
        for l in range(num_layers):
            for d in range(self.num_directions):
                in_s = input_size if l == 0 else hidden_size * self.num_directions
                suffix = f"_l{l}" + ("_reverse" if d == 1 else "")
                self._params.append(_cell_params(self, in_s, hidden_size,
                                                 self.gates, suffix))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        time_major = self.time_major
        nl, nd, hs = self.num_layers, self.num_directions, self.hidden_size
        mode = self.mode
        activation = self.activation
        is_lstm = mode == "lstm"
        param_tensors = [p for quad in self._params for p in quad]

        def f(x, *flat):
            a = x if time_major else jnp.swapaxes(x, 0, 1)  # (T, B, C)
            T, B = a.shape[0], a.shape[1]
            params = [flat[i * 4:(i + 1) * 4] for i in range(nl * nd)]
            h_finals, c_finals = [], []
            layer_in = a
            for l in range(nl):
                outs_dir = []
                for d in range(nd):
                    wi, wh, bi, bh = params[l * nd + d]
                    h0 = jnp.zeros((B, hs), a.dtype)
                    c0 = jnp.zeros((B, hs), a.dtype)
                    seq = layer_in if d == 0 else jnp.flip(layer_in, 0)

                    def step(carry, x_t):
                        h, c = carry
                        h2, c2 = _rnn_step(mode, x_t, h, c, wi, wh, bi, bh, activation)
                        return (h2, c2 if is_lstm else c), h2

                    (h_f, c_f), ys = jax.lax.scan(step, (h0, c0), seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    outs_dir.append(ys)
                    h_finals.append(h_f)
                    c_finals.append(c_f)
                layer_in = jnp.concatenate(outs_dir, -1) if nd == 2 else outs_dir[0]
            out = layer_in if time_major else jnp.swapaxes(layer_in, 0, 1)
            h_stack = jnp.stack(h_finals, 0)
            if is_lstm:
                return out, h_stack, jnp.stack(c_finals, 0)
            return out, h_stack

        outs = execute(f, inputs, *param_tensors, _name=self.mode)
        if is_lstm:
            out, h, c = outs
            return out, (h, c)
        out, h = outs
        return out, h


class SimpleRNN(_RNNBase):
    mode = "rnn"
    gates = 1


class GRU(_RNNBase):
    mode = "gru"
    gates = 3

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        kw.pop("activation", None)
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kw)


class LSTM(_RNNBase):
    mode = "lstm"
    gates = 4

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 proj_size=0, **kw):
        kw.pop("activation", None)
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kw)
