"""Loss layers. reference: python/paddle/nn/layer/loss.py."""

from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
           "BCEWithLogitsLoss", "SmoothL1Loss", "KLDivLoss", "MarginRankingLoss",
           "HingeEmbeddingLoss", "CosineEmbeddingLoss", "TripletMarginLoss",
           "TripletMarginWithDistanceLoss", "MultiLabelSoftMarginLoss",
           "SoftMarginLoss", "PoissonNLLLoss", "GaussianNLLLoss", "CTCLoss"]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.kwargs = dict(ignore_index=ignore_index, reduction=reduction,
                           soft_label=soft_label, axis=axis,
                           use_softmax=use_softmax, label_smoothing=label_smoothing)

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight, **self.kwargs)


def _mk(name, fname):
    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        self._args = args
        self._kwargs = {k: v for k, v in kwargs.items() if k != "name"}

    def forward(self, *inputs):
        return getattr(F, fname)(*inputs, *self._args, **self._kwargs)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


MSELoss = _mk("MSELoss", "mse_loss")
L1Loss = _mk("L1Loss", "l1_loss")
NLLLoss = _mk("NLLLoss", "nll_loss")
BCELoss = _mk("BCELoss", "binary_cross_entropy")
BCEWithLogitsLoss = _mk("BCEWithLogitsLoss", "binary_cross_entropy_with_logits")
SmoothL1Loss = _mk("SmoothL1Loss", "smooth_l1_loss")
KLDivLoss = _mk("KLDivLoss", "kl_div")
MarginRankingLoss = _mk("MarginRankingLoss", "margin_ranking_loss")
HingeEmbeddingLoss = _mk("HingeEmbeddingLoss", "hinge_embedding_loss")
CosineEmbeddingLoss = _mk("CosineEmbeddingLoss", "cosine_embedding_loss")
TripletMarginLoss = _mk("TripletMarginLoss", "triplet_margin_loss")
TripletMarginWithDistanceLoss = _mk("TripletMarginWithDistanceLoss",
                                    "triplet_margin_with_distance_loss")
MultiLabelSoftMarginLoss = _mk("MultiLabelSoftMarginLoss", "multi_label_soft_margin_loss")
SoftMarginLoss = _mk("SoftMarginLoss", "soft_margin_loss")
PoissonNLLLoss = _mk("PoissonNLLLoss", "poisson_nll_loss")
GaussianNLLLoss = _mk("GaussianNLLLoss", "gaussian_nll_loss")


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)
