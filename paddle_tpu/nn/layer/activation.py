"""Activation layers. reference: python/paddle/nn/layer/activation.py."""

from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["ReLU", "ReLU6", "ELU", "SELU", "CELU", "GELU", "Sigmoid", "Tanh",
           "Softmax", "LogSoftmax", "LogSigmoid", "Hardshrink", "Hardsigmoid",
           "Hardswish", "Hardtanh", "LeakyReLU", "PReLU", "RReLU", "Mish",
           "Silu", "Swish", "Softplus", "Softshrink", "Softsign", "Tanhshrink",
           "ThresholdedReLU", "Maxout", "GLU"]


def _mk(name, fname, *defaults):
    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        self._args = args
        self._kwargs = {k: v for k, v in kwargs.items() if k != "name"}

    def forward(self, x):
        return getattr(F, fname)(x, *self._args, **self._kwargs)

    cls = type(name, (Layer,), {"__init__": __init__, "forward": forward})
    return cls


ReLU = _mk("ReLU", "relu")
ReLU6 = _mk("ReLU6", "relu6")
ELU = _mk("ELU", "elu")
SELU = _mk("SELU", "selu")
CELU = _mk("CELU", "celu")
GELU = _mk("GELU", "gelu")
Sigmoid = _mk("Sigmoid", "sigmoid")
Tanh = _mk("Tanh", "tanh")
LogSigmoid = _mk("LogSigmoid", "log_sigmoid")
Hardshrink = _mk("Hardshrink", "hardshrink")
Hardsigmoid = _mk("Hardsigmoid", "hardsigmoid")
Hardswish = _mk("Hardswish", "hardswish")
Hardtanh = _mk("Hardtanh", "hardtanh")
LeakyReLU = _mk("LeakyReLU", "leaky_relu")
Mish = _mk("Mish", "mish")
Silu = _mk("Silu", "silu")
Swish = _mk("Swish", "swish")
Softplus = _mk("Softplus", "softplus")
Softshrink = _mk("Softshrink", "softshrink")
Softsign = _mk("Softsign", "softsign")
Tanhshrink = _mk("Tanhshrink", "tanhshrink")
ThresholdedReLU = _mk("ThresholdedReLU", "thresholded_relu")
Maxout = _mk("Maxout", "maxout")
GLU = _mk("GLU", "glu")
Softmax = _mk("Softmax", "softmax")
LogSoftmax = _mk("LogSoftmax", "log_softmax")
RReLU = _mk("RReLU", "rrelu")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        from .. import initializer as I
        self._data_format = data_format
        self.weight = self.create_parameter((num_parameters,), attr=weight_attr,
                                            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
