"""Parameter initializers. reference: python/paddle/nn/initializer/.

Each initializer produces a concrete jax array from the global PRNG
(framework/random.py) — initialization happens eagerly on host/TPU before
any sharding, and orbax/GSPMD reshards at first use if needed.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import dtypes as _dt
from ...framework.core import Tensor
from ...framework.random import next_key

__all__ = [
    "Bilinear",
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain", "set_global_initializer",
]

_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init, _global_bias_init = weight_init, bias_init


class Initializer:
    def _init(self, shape, dtype):
        raise NotImplementedError

    def __call__(self, param, block=None):
        if isinstance(param, Tensor):
            param._data = self._init(tuple(param._data.shape), param._data.dtype)
            return param
        raise TypeError("initializer expects a Tensor")


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _init(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _init(self, shape, dtype):
        return self.mean + self.std * jax.random.normal(next_key(), shape, dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def _init(self, shape, dtype):
        z = jax.random.truncated_normal(next_key(), self.a, self.b, shape, dtype)
        return self.mean + self.std * z


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def _init(self, shape, dtype):
        return jax.random.uniform(next_key(), shape, dtype, self.low, self.high)


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle linear weight is (in, out)
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(next_key(), shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), shape, dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def _init(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(next_key(), shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def _init(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_key(), shape, dtype, -limit, limit)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def _init(self, shape, dtype):
        arr = self.value._data if isinstance(self.value, Tensor) else jnp.asarray(np.asarray(self.value))
        return arr.astype(dtype).reshape(shape)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def _init(self, shape, dtype):
        return self.gain * jax.nn.initializers.orthogonal()(next_key(), shape, dtype)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def _init(self, shape, dtype):
        # conv kernel (out, in, *spatial): identity-preserving init
        arr = np.zeros(shape, np.float32)
        out_c, in_c = shape[0], shape[1]
        centers = tuple(s // 2 for s in shape[2:])
        per_group = out_c // self.groups
        for g in range(self.groups):
            for i in range(min(per_group, in_c)):
                arr[(g * per_group + i, i) + centers] = 1.0
        return jnp.asarray(arr, dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity in ("sigmoid", "conv1d", "conv2d", "conv3d", "linear",
                        "conv_transpose1d", "conv_transpose2d", "conv_transpose3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3.0
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4.0
    raise ValueError(f"unsupported nonlinearity {nonlinearity}")


class Bilinear(Initializer):
    """Bilinear upsampling kernel init (for transposed convs).
    reference: nn/initializer/Bilinear."""

    def __call__(self, t):
        import numpy as np
        import jax.numpy as jnp
        shape = tuple(t.shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        kh, kw = shape[2], shape[3]
        f = np.ceil(kw / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        grid = np.zeros(shape, np.float32)
        for i in range(kh):
            for j in range(kw):
                grid[:, :, i, j] = (1 - abs(i / f - c)) * (1 - abs(j / f - c))
        t._data = jnp.asarray(grid).astype(t._data.dtype)
        return t
