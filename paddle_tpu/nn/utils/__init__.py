"""nn.utils. reference: python/paddle/nn/utils/."""

from __future__ import annotations

import jax.numpy as jnp

from ...framework.core import Tensor
from ..clip import clip_grad_norm_, clip_grad_value_  # noqa: F401

__all__ = ["parameters_to_vector", "vector_to_parameters", "weight_norm",
           "remove_weight_norm", "spectral_norm", "clip_grad_norm_",
           "clip_grad_value_"]


def parameters_to_vector(parameters, name=None):
    from ...tensor.manipulation import concat, reshape
    return concat([reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p._data = vec._data[offset:offset + n].reshape(p._data.shape)
        offset += n


def weight_norm(layer, name="weight", dim=0):
    import numpy as np
    w = getattr(layer, name)
    arr = w._data
    if dim is None:
        g = jnp.linalg.norm(arr)
        v = arr
    else:
        axes = tuple(i for i in range(arr.ndim) if i != dim)
        g = jnp.sqrt(jnp.sum(arr * arr, axis=axes))
        v = arr
    from ...framework.core import Parameter
    layer.add_parameter(name + "_g", Parameter(g))
    layer.add_parameter(name + "_v", Parameter(v))
    del layer._parameters[name]

    def hook(l, inputs):
        g_ = getattr(l, name + "_g")
        v_ = getattr(l, name + "_v")
        from ...framework.core import execute
        def f(gv, vv):
            if dim is None:
                w_ = vv * (gv / jnp.linalg.norm(vv))
            else:
                axes = tuple(i for i in range(vv.ndim) if i != dim)
                norm = jnp.sqrt(jnp.sum(vv * vv, axis=axes, keepdims=True))
                shape = [1] * vv.ndim
                shape[dim] = -1
                w_ = vv / norm * gv.reshape(shape)
            return w_
        w_t = execute(f, g_, v_, _name="weight_norm")
        object.__setattr__(l, "_wn_cached", w_t)
        l._parameters.pop(name, None)
        l.__dict__[name] = w_t
    layer._wn_hook = layer.register_forward_pre_hook(hook)
    hook(layer, None)
    return layer


def remove_weight_norm(layer, name="weight"):
    from ...framework.core import Parameter
    w = layer.__dict__.get(name)
    if hasattr(layer, "_wn_hook"):
        layer._wn_hook.remove()
    g = layer._parameters.pop(name + "_g", None)
    v = layer._parameters.pop(name + "_v", None)
    if w is not None:
        layer.add_parameter(name, Parameter(w._data))
        layer.__dict__.pop(name, None)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    from ..layer.norm import SpectralNorm
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = SpectralNorm(tuple(w._data.shape), dim=dim, power_iters=n_power_iterations,
                      epsilon=eps)
    layer.add_sublayer(name + "_sn", sn)
    orig = layer._parameters[name]

    def hook(l, inputs):
        w_t = sn(orig)
        l._parameters.pop(name, None)
        l.__dict__[name] = w_t
    layer._sn_hook = layer.register_forward_pre_hook(hook)
    return layer
