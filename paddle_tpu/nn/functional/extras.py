"""Long-tail nn.functional ops closing the reference surface.

reference: python/paddle/nn/functional/ — distance.py (pairwise_distance),
vision.py (grid_sample, affine_grid, pixel ops, temporal_shift),
pooling.py (max_unpool*, fractional pools), loss.py (multi_margin_loss,
hsigmoid_loss), flash_attention.py (qkv-packed wrappers).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, execute
from ...framework.random import next_key

__all__ = [
    "pairwise_distance", "grid_sample", "affine_grid", "max_unpool1d",
    "max_unpool2d", "max_unpool3d", "temporal_shift",
    "feature_alpha_dropout", "multi_margin_loss", "hsigmoid_loss",
    "fractional_max_pool2d", "fractional_max_pool3d", "gather_tree",
    "flash_attn_qkvpacked", "flash_attn_varlen_qkvpacked",
    "flashmask_attention", "margin_cross_entropy", "class_center_sample",
    "sparse_attention", "rnnt_loss", "adaptive_log_softmax_with_loss",
]


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """reference: nn/functional/distance.py pairwise_distance."""
    def f(a, b):
        d = a - b
        if p == float("inf"):
            out = jnp.max(jnp.abs(d), axis=-1, keepdims=keepdim)
        elif p == float("-inf"):
            out = jnp.min(jnp.abs(d), axis=-1, keepdims=keepdim)
        else:
            out = jnp.sum((jnp.abs(d) + epsilon) ** p, axis=-1,
                          keepdims=keepdim) ** (1.0 / p)
        return out
    return execute(f, x, y, _name="pairwise_distance")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2D affine sampling grid. reference: nn/functional/vision.py
    affine_grid. theta: (N, 2, 3); out_shape (N, C, H, W) -> (N, H, W, 2)."""
    n, _, h, w = [int(s) for s in out_shape]

    def base(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        return (jnp.arange(size) * 2 + 1) / size - 1.0

    def f(th):
        ys = base(h)
        xs = base(w)
        gx, gy = jnp.meshgrid(xs, ys)               # (h, w)
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx, gy, ones], -1)      # (h, w, 3)
        out = jnp.einsum("hwk,njk->nhwj", coords, th)
        return out.astype(th.dtype)
    return execute(f, theta, _name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x (N,C,H,W) at normalized grid (N,Hg,Wg,2) coordinates.
    reference: nn/functional/vision.py grid_sample (bilinear/nearest,
    zeros/border/reflection padding)."""
    def f(a, g):
        n, c, h, w = a.shape
        gx = g[..., 0]
        gy = g[..., 1]

        def unnorm(v, size):
            if align_corners:
                return (v + 1.0) * (size - 1) / 2.0
            return ((v + 1.0) * size - 1.0) / 2.0

        fx = unnorm(gx, w)
        fy = unnorm(gy, h)

        def reflect(v, lo, hi):
            rng = hi - lo
            v = jnp.abs(jnp.mod(v - lo, 2 * rng) - rng) + lo \
                if rng > 0 else jnp.zeros_like(v)
            return v

        if padding_mode == "border":
            fx = jnp.clip(fx, 0, w - 1)
            fy = jnp.clip(fy, 0, h - 1)
        elif padding_mode == "reflection":
            if align_corners:
                fx = reflect(fx, 0.0, w - 1.0)
                fy = reflect(fy, 0.0, h - 1.0)
            else:
                fx = jnp.clip(reflect(fx, -0.5, w - 0.5), 0, w - 1)
                fy = jnp.clip(reflect(fy, -0.5, h - 0.5), 0, h - 1)

        def gather(ix, iy):
            valid = ((ix >= 0) & (ix <= w - 1)
                     & (iy >= 0) & (iy <= h - 1))
            ixc = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
            iyc = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
            # (n, c, hg, wg): batch-index the spatial grid per sample
            bidx = jnp.arange(n)[:, None, None]
            vals = a[bidx, :, iyc, ixc]             # (n, hg, wg, c)
            vals = jnp.moveaxis(vals, -1, 1)
            if padding_mode == "zeros":
                vals = vals * valid[:, None, :, :].astype(a.dtype)
            return vals

        if mode == "nearest":
            return gather(jnp.round(fx), jnp.round(fy))
        x0 = jnp.floor(fx)
        y0 = jnp.floor(fy)
        x1 = x0 + 1
        y1 = y0 + 1
        wa = ((x1 - fx) * (y1 - fy))[:, None]
        wb = ((fx - x0) * (y1 - fy))[:, None]
        wc = ((x1 - fx) * (fy - y0))[:, None]
        wd = ((fx - x0) * (fy - y0))[:, None]
        out = (gather(x0, y0) * wa + gather(x1, y0) * wb
               + gather(x0, y1) * wc + gather(x1, y1) * wd)
        return out.astype(a.dtype)
    return execute(f, x, grid, _name="grid_sample")


def _max_unpool(x, indices, ndim, kernel_size, stride=None, padding=0,
                output_size=None, data_format=None, name=None):
    from .pooling import _tuple
    ks = _tuple(kernel_size, ndim)
    sd = _tuple(stride if stride is not None else kernel_size, ndim)

    def f(a, idx):
        spatial_in = a.shape[2:]
        if output_size is not None:
            out_sp = tuple(int(s) for s in output_size[-ndim:])
        else:
            out_sp = tuple((si - 1) * st + k
                           for si, st, k in zip(spatial_in, sd, ks))
        n, c = a.shape[:2]
        flat_sp = int(np.prod(out_sp))
        out = jnp.zeros((n, c, flat_sp), a.dtype)
        out = out.at[jnp.arange(n)[:, None, None],
                     jnp.arange(c)[None, :, None],
                     idx.reshape(n, c, -1)].set(a.reshape(n, c, -1))
        return out.reshape((n, c) + out_sp)
    return execute(f, x, indices, _name=f"max_unpool{ndim}d")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """reference: nn/functional/pooling.py max_unpool1d — scatter pooled
    values back to their argmax positions (indices flat over L)."""
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """reference: nn/functional/pooling.py max_unpool2d (indices flat over
    H*W, as produced by max_pool2d(return_mask=True))."""
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size)


def fractional_max_pool2d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False, name=None):
    """Fractional max pooling (Graham 2014): pseudo-random pooling regions
    whose sizes average H/out. reference: nn/functional/pooling.py.
    Deterministic given random_u; drawn from the global RNG otherwise."""
    def region_starts(in_size, out_size, u):
        alpha = in_size / out_size
        idx = jnp.floor(alpha * (jnp.arange(out_size) + u)).astype(jnp.int32)
        idx = jnp.clip(idx, 0, in_size - 1)
        return jnp.concatenate([jnp.zeros((1,), jnp.int32), idx[1:]]), \
            jnp.concatenate([idx[1:], jnp.asarray([in_size], jnp.int32)])

    if random_u is None:
        u = float(jax.random.uniform(next_key(), ()))
    else:
        u = float(random_u)
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))

    def f(a):
        n, c, h, w = a.shape
        hs, he = region_starts(h, oh, u)
        ws, we = region_starts(w, ow, u)
        max_kh = int(np.ceil(h / oh)) + 1
        max_kw = int(np.ceil(w / ow)) + 1

        kh = min(max_kh, h)
        kw = min(max_kw, w)

        def pool_cell(i, j):
            # dynamic_slice clamps starts near the edge; clamp explicitly so
            # the row/col labels match what was actually sliced
            ys = jnp.minimum(hs[i], h - kh)
            xs = jnp.minimum(ws[j], w - kw)
            patch = jax.lax.dynamic_slice(a, (0, 0, ys, xs), (n, c, kh, kw))
            yy = jnp.arange(kh) + ys
            xx = jnp.arange(kw) + xs
            m = ((yy[:, None] >= hs[i]) & (yy[:, None] < he[i])
                 & (xx[None, :] >= ws[j]) & (xx[None, :] < we[j]))
            patch = jnp.where(m[None, None], patch, -jnp.inf)
            return jnp.max(patch, axis=(2, 3))

        cols = [jnp.stack([pool_cell(i, j) for j in range(ow)], -1)
                for i in range(oh)]
        return jnp.stack(cols, -2)
    out = execute(f, x, _name="fractional_max_pool2d")
    return (out, None) if return_mask else out


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """3D via a depth loop over the 2D kernel (depth regions use the same
    pseudo-random sequence)."""
    od, oh, ow = (output_size if isinstance(output_size, (tuple, list))
                  else (output_size,) * 3)
    u = float(random_u) if random_u is not None else float(
        jax.random.uniform(next_key(), ()))

    def f(a):
        n, c, d, h, w = a.shape
        alpha = d / od
        starts = np.floor(alpha * (np.arange(od) + u)).astype(np.int32)
        starts = np.clip(starts, 0, d - 1)
        starts[0] = 0
        ends = np.append(starts[1:], d)
        slabs = []
        for i in range(od):
            slab = jnp.max(a[:, :, starts[i]:ends[i]], axis=2)
            sub = fractional_max_pool2d(Tensor(slab), (oh, ow), random_u=u)
            slabs.append(sub._data if isinstance(sub, Tensor) else sub)
        return jnp.stack(slabs, axis=2)
    out = execute(f, x, _name="fractional_max_pool3d")
    return (out, None) if return_mask else out


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM channel shift across the time axis.
    reference: nn/functional/vision.py temporal_shift."""
    def f(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate(
            [v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        right = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, fold:2 * fold]),
             v[:, :-1, fold:2 * fold]], axis=1)
        keep = v[:, :, 2 * fold:]
        return jnp.concatenate([left, right, keep], axis=2).reshape(
            nt, c, h, w)
    return execute(f, x, _name="temporal_shift")


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Alpha dropout over whole channels (SELU-preserving).
    reference: nn/functional/common.py feature_alpha_dropout."""
    if not training or p == 0.0:
        return execute(lambda a: a, x, _name="feature_alpha_dropout")
    alpha = -1.7580993408473766
    key = next_key()

    def f(a):
        shape = (a.shape[0], a.shape[1]) + (1,) * (a.ndim - 2)
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        q = 1.0 - p
        scale_a = (q + alpha ** 2 * q * (1 - q)) ** -0.5
        scale_b = -scale_a * alpha * (1 - q)
        return (jnp.where(keep, a, alpha) * scale_a + scale_b).astype(a.dtype)
    return execute(f, x, _name="feature_alpha_dropout")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """reference: nn/functional/loss.py multi_margin_loss."""
    args = [input, label] + ([weight] if weight is not None else [])

    def f(logits, lab, *rest):
        n, c = logits.shape
        correct = jnp.take_along_axis(logits, lab[:, None], 1)
        m = jnp.maximum(margin - correct + logits, 0.0) ** p
        if rest:
            m = m * rest[0][lab][:, None]
        mask = jnp.arange(c)[None, :] != lab[:, None]
        loss = jnp.sum(m * mask, axis=1) / c
        if reduction == "mean":
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return execute(f, *args, _name="multi_margin_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid over the default complete binary tree.
    reference: nn/functional/loss.py hsigmoid_loss (custom trees via
    path_table/path_code)."""
    if path_table is not None or path_code is not None:
        raise NotImplementedError(
            "hsigmoid_loss: custom trees (path_table/path_code) are not "
            "supported; use the default complete binary tree")
    depth = int(np.ceil(np.log2(max(num_classes, 2))))
    # complete-binary-tree paths (leaf cls+num_classes up to the root node 1);
    # paths are ragged for non-power-of-2 num_classes, so levels carry a
    # validity mask instead of underflowing into the root weight
    codes = np.zeros((num_classes, depth), np.float32)
    nodes = np.zeros((num_classes, depth), np.int32)
    valid = np.zeros((num_classes, depth), np.float32)
    for cls in range(num_classes):
        node = cls + num_classes  # leaves occupy [num_classes, 2*num_classes)
        lvl = depth - 1
        while node > 1 and lvl >= 0:
            codes[cls, lvl] = node % 2
            node //= 2
            nodes[cls, lvl] = node - 1  # internal 1..num_classes-1 -> 0-based
            valid[cls, lvl] = 1.0
            lvl -= 1
    codes_j = jnp.asarray(codes)
    nodes_j = jnp.asarray(nodes)
    valid_j = jnp.asarray(valid)
    args = [input, label, weight] + ([bias] if bias is not None else [])

    def f(a, lab, w, *rest):
        path_nodes = nodes_j[lab]                    # (n, depth)
        path_codes = codes_j[lab]
        wv = w[path_nodes]                           # (n, depth, dim)
        logits = jnp.einsum("nd,nkd->nk", a, wv)
        if rest:
            logits = logits + rest[0][path_nodes]
        # sigmoid cross-entropy against the path code at every VALID level
        lvl_loss = (jnp.maximum(logits, 0) - logits * path_codes
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))
        loss = jnp.sum(lvl_loss * valid_j[lab], axis=1)
        return jnp.mean(loss)
    return execute(f, *args, _name="hsigmoid_loss")


def gather_tree(ids, parents):
    """Walk beam-search parent pointers backward to recover full sequences.
    reference: nn/functional/gather_tree (fluid beam search)."""
    def f(i, p):
        t, b, k = i.shape  # (max_time, batch, beam)

        def step(carry, xs):
            beam_idx = carry
            ids_t, par_t = xs
            picked = jnp.take_along_axis(ids_t, beam_idx, axis=1)
            parent = jnp.take_along_axis(par_t, beam_idx, axis=1)
            return parent, picked

        init = jnp.broadcast_to(jnp.arange(k)[None, :], (b, k))
        _, out = jax.lax.scan(step, init, (i[::-1], p[::-1]))
        return out[::-1]
    return execute(f, ids, parents, _name="gather_tree")


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, *, fixed_seed_offset=None,
                         rng_name="", training=True, name=None):
    """qkv: (batch, seq, 3, num_heads, head_dim).
    reference: nn/functional/flash_attention.py flash_attn_qkvpacked."""
    from .attention import flash_attention
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax, training=training)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale,
                                dropout=0.0, causal=False,
                                return_softmax=False, **kw):
    """qkv: (total_tokens, 3, num_heads, head_dim) packed varlen."""
    from .attention import flash_attn_unpadded
    q = qkv[:, 0]
    k = qkv[:, 1]
    v = qkv[:, 2]
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale,
                               dropout=dropout, causal=causal,
                               return_softmax=return_softmax)


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False, window_size=None,
                        return_softmax_lse=False, return_seed_offset=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """FlashMask: column-sparse attention masks encoded as start/end row
    indices. reference: nn/functional/flash_attention.py
    flashmask_attention (the FlashMask paper's kernel).

    TPU design: the startend encoding expands to a dense additive mask and
    runs through scaled_dot_product_attention — XLA fuses the mask add; a
    Pallas block-skipping kernel is the later optimization. Supported
    encodings: (b, h|1, sk, 1) = causal LT mask [start], and
    (b, h|1, sk, 2) = LT [start, end)."""
    from .attention import scaled_dot_product_attention
    if startend_row_indices is None:
        return scaled_dot_product_attention(
            query, key, value, dropout_p=dropout, is_causal=causal,
            training=training), None

    sq = int(query.shape[1])
    sk = int(key.shape[1])

    def build_mask(se):
        rows = jnp.arange(sq)[:, None]              # query index
        cols = jnp.arange(sk)[None, :]              # key index
        start = se[..., 0]                          # (b, h, sk)
        # masked when row >= start[col] (values AFTER start are blocked)
        blocked = rows[None, None] >= start[:, :, None, :]
        if se.shape[-1] == 2:
            end = se[..., 1]
            blocked = blocked & (rows[None, None] < end[:, :, None, :])
        if causal:
            blocked = blocked | (rows < cols)[None, None]
        return jnp.where(blocked, jnp.float32(-1e30), jnp.float32(0.0))

    se = startend_row_indices
    se_arr = se._data if isinstance(se, Tensor) else jnp.asarray(se)
    mask = Tensor(build_mask(se_arr))
    out = scaled_dot_product_attention(query, key, value, attn_mask=mask,
                                       dropout_p=dropout, is_causal=False,
                                       training=training)
    return out, None


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace/CosFace-family margin softmax.
    reference: nn/functional/common.py margin_cross_entropy — the target
    logit cos(theta) becomes cos(m1*theta + m2) - m3, all logits scale by s.
    Single-controller: class-parallel (group) sharding is GSPMD's job when
    the weight is sharded; the math here is the local formula."""
    def f(lg, lab):
        n, c = lg.shape
        target = jnp.take_along_axis(lg, lab[:, None], 1)[:, 0]
        target = jnp.clip(target, -1.0 + 1e-6, 1.0 - 1e-6)
        theta = jnp.arccos(target)
        m_target = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(lab, c, dtype=lg.dtype)
        adjusted = lg + onehot * (m_target[:, None] - target[:, None])
        adjusted = adjusted * scale
        lp = jax.nn.log_softmax(adjusted, -1)
        loss = -jnp.take_along_axis(lp, lab[:, None], 1)[:, 0]
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jnp.exp(lp)
        return loss
    return execute(f, logits, label, _name="margin_cross_entropy")


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample class centers: all positive classes + random negatives.
    reference: nn/functional/common.py class_center_sample (PartialFC).
    Returns (remapped_label, sampled_class_indices). Eager (data-dependent
    output size belongs on host, like the reference's CPU sampling step)."""
    lab = np.asarray(label._data if isinstance(label, Tensor) else label)
    pos = np.unique(lab)
    n_extra = max(int(num_samples) - pos.size, 0)
    rest = np.setdiff1d(np.arange(num_classes), pos)
    rng = np.random.default_rng(int(abs(int(lab.sum())) % (2**31)))
    neg = rng.choice(rest, size=min(n_extra, rest.size), replace=False) \
        if rest.size else np.empty((0,), lab.dtype)
    sampled = np.concatenate([pos, np.sort(neg)]).astype(lab.dtype)
    remap = {c: i for i, c in enumerate(sampled.tolist())}
    remapped = np.asarray([remap[c] for c in lab.tolist()], lab.dtype)
    return Tensor(jnp.asarray(remapped)), Tensor(jnp.asarray(sampled))


def sparse_attention(x, offset, columns, query, key, value, sparse_mask=None,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block/CSR-sparse attention: row i may attend only to
    columns[offset[i]:offset[i+1]].
    reference: nn/functional/sparse_attention.py (GPU CSR kernel).

    TPU design: the CSR pattern expands to a dense boolean mask (static
    shapes; XLA fuses the mask) — the Pallas block-skipping kernel is the
    later optimization. Signature kept positional-compatible; `x` may be
    None (the reference passes q/k/v explicitly)."""
    def f(q, k, v, off, cols):
        b, h, sq, d = q.shape
        sk = k.shape[2]

        def one_mask(off1, cols1):
            row_ids = jnp.searchsorted(off1, jnp.arange(cols1.shape[-1]),
                                       side="right") - 1
            m = jnp.zeros((sq, sk), jnp.bool_)
            return m.at[row_ids, cols1].set(True)

        if off.ndim == 1:  # shared pattern
            mask = one_mask(off, cols)[None, None]
        else:  # reference layout: (B, H, sq+1) / (B, H, nnz)
            mask = jax.vmap(jax.vmap(one_mask))(
                off.reshape(b, -1, off.shape[-1]),
                cols.reshape(b, -1, cols.shape[-1]))
            if mask.shape[1] == 1 and h > 1:
                mask = jnp.broadcast_to(mask, (b, h, sq, sk))
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        logits = logits / jnp.sqrt(jnp.float32(d))
        logits = jnp.where(mask, logits, jnp.float32(-1e30))
        probs = jax.nn.softmax(logits, -1)
        probs = jnp.where(mask, probs, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)
    return execute(f, query, key, value, offset, columns,
                   _name="sparse_attention")


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss: -log P(label | acoustics) summed over all
    monotonic alignments. reference: nn/functional/loss.py rnnt_loss
    (warprnnt CUDA kernel).

    TPU design: the forward DP over the (T, U) lattice runs as a lax.scan
    over time frames; the in-row dependency (emit from u-1) is a second
    scan over label positions. logits: (B, T, U+1, V)."""
    def f(logits, lab, ilen, llen):
        bsz, t_max, u_max, v = logits.shape  # u_max = U+1
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        blank_lp = lp[..., blank]                       # (B, T, U+1)
        lab_idx = jnp.minimum(lab, v - 1)
        emit_lp = jnp.take_along_axis(
            lp[:, :, :-1, :], lab_idx[:, None, :, None], -1)[..., 0]
        emit_lp = jnp.pad(emit_lp, ((0, 0), (0, 0), (0, 1)),
                          constant_values=-1e30)        # (B, T, U+1)
        NEG = jnp.float32(-1e30)

        def emit_scan(alpha_row, emit_row):
            # alpha_row: (B, U+1) pre-emit; fold in emissions left-to-right
            def inner(carry, u):
                prev = carry                             # alpha[t, u-1] final
                cur = jnp.where(u == 0, alpha_row[:, 0],
                                jnp.logaddexp(alpha_row[jnp.arange(bsz), u],
                                              prev + emit_row[
                                                  jnp.arange(bsz), u - 1]))
                return cur, cur
            _, rows = jax.lax.scan(inner, jnp.full((bsz,), NEG),
                                   jnp.arange(u_max))
            return jnp.moveaxis(rows, 0, 1)              # (B, U+1)

        alpha0 = jnp.full((bsz, u_max), NEG).at[:, 0].set(0.0)
        alpha0 = emit_scan(alpha0, emit_lp[:, 0])

        def time_step(alpha, t):
            from_blank = alpha + blank_lp[:, t - 1]      # advance time
            new = emit_scan(from_blank, emit_lp[:, t])
            return jnp.where((t < ilen[:, None]), new, alpha), None

        alpha, _ = jax.lax.scan(time_step, alpha0, jnp.arange(1, t_max))
        last_t = jnp.clip(ilen - 1, 0, t_max - 1)
        final_blank = blank_lp[jnp.arange(bsz), last_t, llen]
        ll = alpha[jnp.arange(bsz), llen] + final_blank
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return execute(f, input, label, input_lengths, label_lengths,
                   _name="rnnt_loss")


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (Grave et al.): frequent classes in the head,
    rare classes in down-projected tail clusters.
    reference: nn/functional/activation.py adaptive_log_softmax_with_loss.
    Returns (per-sample log-prob output, scalar loss)."""
    n_clusters = len(cutoffs)  # cutoffs excludes the final num_classes
    args = [input, label, head_weight] + list(
        w for pair in tail_weights for w in pair)
    if head_bias is not None:
        args.append(head_bias)

    def f(a, lab, hw, *rest):
        tails = [(rest[2 * i], rest[2 * i + 1]) for i in range(n_clusters)]
        hb = rest[2 * n_clusters] if head_bias is not None else None
        head_logits = a @ hw
        if hb is not None:
            head_logits = head_logits + hb
        head_lp = jax.nn.log_softmax(head_logits, -1)    # (N, c0+K)
        c0 = head_logits.shape[-1] - n_clusters
        # head classes: direct log-prob
        out = jnp.where(lab < c0,
                        jnp.take_along_axis(
                            head_lp, jnp.clip(lab, 0, c0 - 1)[:, None],
                            1)[:, 0],
                        0.0)
        lo = c0
        for i, (proj, cls_w) in enumerate(tails):
            hi = cutoffs[i + 1] if i + 1 < len(cutoffs) else None
            size = cls_w.shape[-1]
            in_cluster = (lab >= lo) & (lab < lo + size)
            tail_lp = jax.nn.log_softmax((a @ proj) @ cls_w, -1)
            rel = jnp.clip(lab - lo, 0, size - 1)
            lp_i = head_lp[:, c0 + i] + jnp.take_along_axis(
                tail_lp, rel[:, None], 1)[:, 0]
            out = jnp.where(in_cluster, lp_i, out)
            lo += size
        return out, -jnp.mean(out)
    return execute(f, *args, _name="adaptive_log_softmax_with_loss")
