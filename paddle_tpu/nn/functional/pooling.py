"""Pooling via lax.reduce_window. reference: python/paddle/nn/functional/pooling.py."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import execute

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
           "max_pool2d", "max_pool3d", "adaptive_avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool3d", "adaptive_max_pool1d",
           "adaptive_max_pool2d", "adaptive_max_pool3d", "lp_pool1d", "lp_pool2d"]


def _tuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(int(x) for x in v)


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    p = list(padding)
    if len(p) == n:
        return [(int(v), int(v)) for v in p]
    if len(p) == 2 * n:
        return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _ceil_extra(pad, spatial, ks, sd):
    """Extra right-padding per spatial dim so reduce_window emits the
    reference's ceil_mode output size — including the clamp that drops a
    last window which would START beyond input + left pad (otherwise that
    window covers only padding: 0/0 NaN for avg, -inf for max)."""
    import math
    extra = []
    for L, (lo, hi), k, s in zip(spatial, pad, ks, sd):
        total = L + lo + hi
        out = math.ceil(max(total - k, 0) / s) + 1
        if (out - 1) * s >= L + lo:
            out -= 1
        extra.append(max((out - 1) * s + k - total, 0))
    return extra


def _pool_geometry(a_shape, ks, sd, pad, n, channels_first, ceil_mode):
    """(window, strides, pads) for reduce_window, with ceil_mode folded
    into extra right-padding. pads may be a SAME/VALID string."""
    if isinstance(pad, str):
        if ceil_mode:
            raise ValueError("ceil_mode with string padding is unsupported")
        return ((1, 1) + ks if channels_first else (1,) + ks + (1,),
                (1, 1) + sd if channels_first else (1,) + sd + (1,),
                pad)
    spatial = a_shape[2:2 + n] if channels_first else a_shape[1:1 + n]
    pad = [list(p) for p in pad]
    if ceil_mode:
        for p, e in zip(pad, _ceil_extra(pad, spatial, ks, sd)):
            p[1] += e
    pad = [tuple(p) for p in pad]
    if channels_first:
        return (1, 1) + ks, (1, 1) + sd, [(0, 0), (0, 0)] + pad
    return (1,) + ks + (1,), (1,) + sd + (1,), [(0, 0)] + pad + [(0, 0)]


def _pool(x, kernel, stride, padding, n, kind, ceil_mode=False, exclusive=True,
          data_format="NCHW"):
    ks = _tuple(kernel, n)
    sd = _tuple(stride if stride is not None else kernel, n)
    pad = _pads(padding, n)
    channels_first = data_format in ("NCL", "NCHW", "NCDHW")

    def f(a):
        window, strides, pads = _pool_geometry(
            a.shape, ks, sd, pad, n, channels_first, ceil_mode)
        if kind == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, init, jax.lax.max, window, strides, pads)
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pads)
        if isinstance(pads, str):
            return s / float(np.prod(ks))
        if exclusive:
            # divisor = REAL elements only (all padding excluded)
            ones = jnp.ones_like(a)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
            return s / cnt
        if ceil_mode:
            # exclusive=False counts user padding in the divisor but must
            # still exclude the synthetic ceil-extra pad: count over a
            # ones tensor pre-padded with 1s in the USER pad region only
            ones = jnp.ones_like(a)
            user = [(0, 0), (0, 0)] + [tuple(p) for p in pad] \
                if channels_first else \
                [(0, 0)] + [tuple(p) for p in pad] + [(0, 0)]
            ones = jnp.pad(ones, user, constant_values=1.0)
            extra = [(po[0] - u[0], po[1] - u[1])
                     for po, u in zip(pads, user)]
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides, extra)
            return s / cnt
        return s / float(np.prod(ks))

    return execute(f, x, _name=f"{kind}_pool{n}d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    out = _pool(x, kernel_size, stride, padding, 1, "max", ceil_mode, data_format=data_format)
    if return_mask:
        return out, _max_pool_indices(x, kernel_size, stride, padding, 1,
                                      ceil_mode, data_format)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, "max", ceil_mode, data_format=data_format)
    if return_mask:
        return out, _max_pool_indices(x, kernel_size, stride, padding, 2,
                                      ceil_mode, data_format)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, "max", ceil_mode, data_format=data_format)
    if return_mask:
        return out, _max_pool_indices(x, kernel_size, stride, padding, 3,
                                      ceil_mode, data_format)
    return out


def _max_pool_indices(x, kernel, stride, padding, n, ceil_mode=False,
                      data_format="NCHW"):
    """Argmax indices (flat over the spatial dims) for max_poolNd's
    return_mask — the contract max_unpoolNd consumes."""
    ks = _tuple(kernel, n)
    sd = _tuple(stride if stride is not None else kernel, n)
    pad = _pads(padding, n)
    channels_first = data_format in ("NCL", "NCHW", "NCDHW")

    def f(a):
        spatial = a.shape[2:2 + n] if channels_first else a.shape[1:1 + n]
        size = int(np.prod(spatial))
        shape = ((1, 1) + tuple(spatial)) if channels_first \
            else ((1,) + tuple(spatial) + (1,))
        # int32 index operand: float32 can only represent integers up to
        # 2^24 exactly, so a float-carried flat index is wrong for large
        # spatial extents (e.g. 4096x4096 2D or 256^3 3D inputs)
        flat_idx = jnp.arange(size, dtype=jnp.int32).reshape(shape)
        flat_idx = jnp.broadcast_to(flat_idx, a.shape)
        big = jnp.where(jnp.isfinite(a), a, -jnp.inf)

        def select(x1, x2):
            v1, i1 = x1
            v2, i2 = x2
            take1 = (v1 > v2) | ((v1 == v2) & (i1 < i2))
            return jnp.where(take1, v1, v2), jnp.where(take1, i1, i2)
        window, strides, pads = _pool_geometry(
            a.shape, ks, sd, pad, n, channels_first, ceil_mode)
        v, i = jax.lax.reduce_window(
            (big, flat_idx), (-jnp.inf, jnp.int32(size)), select,
            window, strides, pads)
        return i.astype(jnp.int64)
    return execute(f, x, _name="max_pool_indices")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", ceil_mode, exclusive, data_format)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", ceil_mode, exclusive, data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", ceil_mode, exclusive, data_format)


def _adaptive(x, output_size, n, kind, data_format="NCHW"):
    os = _tuple(output_size, n)

    def f(a):
        spatial = a.shape[2:2 + n]
        out = a
        for d in range(n):
            in_s, out_s = spatial[d], os[d]
            if out_s is None or out_s == in_s:
                continue
            axis = 2 + d
            starts = (np.arange(out_s) * in_s) // out_s
            ends = ((np.arange(out_s) + 1) * in_s + out_s - 1) // out_s
            slices = []
            for s, e in zip(starts, ends):
                seg = jax.lax.slice_in_dim(out, int(s), int(e), axis=axis)
                red = jnp.max(seg, axis=axis, keepdims=True) if kind == "max" else jnp.mean(seg, axis=axis, keepdims=True)
                slices.append(red)
            out = jnp.concatenate(slices, axis=axis)
        return out

    return execute(f, x, _name=f"adaptive_{kind}_pool{n}d")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 1, "max")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 2, "max")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 3, "max")
    return (out, None) if return_mask else out


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    p = float(norm_type)
    def f(a):
        ap = jnp.abs(a) ** p
        return None
    # implement via avg pool of |x|^p then root
    from ...framework.core import Tensor
    ap = execute(lambda a: jnp.abs(a) ** p, x, _name="lp_pow")
    s = _pool(ap, kernel_size, stride, padding, 1, "avg", ceil_mode, False, data_format)
    ks = _tuple(kernel_size, 1)
    return execute(lambda a: (a * float(np.prod(ks))) ** (1.0 / p), s, _name="lp_root")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)
    ap = execute(lambda a: jnp.abs(a) ** p, x, _name="lp_pow")
    s = _pool(ap, kernel_size, stride, padding, 2, "avg", ceil_mode, False, data_format)
    ks = _tuple(kernel_size, 2)
    return execute(lambda a: (a * float(np.prod(ks))) ** (1.0 / p), s, _name="lp_root")
