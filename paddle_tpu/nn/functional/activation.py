"""Activation functionals. reference: python/paddle/nn/functional/activation.py.

All map to jax.nn / jnp primitives; XLA fuses them into surrounding matmuls
on TPU (the reference needs CINN or hand-fused kernels for the same effect).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import execute

__all__ = [
    "relu", "relu_", "hardtanh_", "leaky_relu_", "thresholded_relu_", "relu6", "elu", "elu_", "selu", "celu", "gelu", "silu",
    "swish", "mish", "softplus", "softshrink", "hardshrink", "tanhshrink",
    "sigmoid", "hardsigmoid", "hardswish", "hardtanh", "leaky_relu",
    "log_sigmoid", "log_softmax", "softmax", "softmax_", "softsign",
    "thresholded_relu", "tanh", "tanh_", "prelu", "rrelu", "maxout",
    "glu", "gumbel_softmax",
]


def _unary(name, f):
    def op(x, name=None):
        return execute(f, x, _name=name)
    op.__name__ = name
    return op


relu = lambda x, name=None: execute(jax.nn.relu, x, _name="relu")
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
tanh = _unary("tanh", jnp.tanh)
silu = _unary("silu", jax.nn.silu)
softsign = _unary("softsign", jax.nn.soft_sign)
log_sigmoid = _unary("log_sigmoid", jax.nn.log_sigmoid)
mish = _unary("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)))


def relu_(x, name=None):
    return x._rebind(relu(x))


def tanh_(x, name=None):
    return x._rebind(tanh(x))


def relu6(x, name=None):
    return execute(jax.nn.relu6, x, _name="relu6")


def elu(x, alpha=1.0, name=None):
    return execute(lambda a: jax.nn.elu(a, alpha), x, _name="elu")


def elu_(x, alpha=1.0, name=None):
    return x._rebind(elu(x, alpha))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return execute(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x, _name="selu")


def celu(x, alpha=1.0, name=None):
    return execute(lambda a: jax.nn.celu(a, alpha), x, _name="celu")


def gelu(x, approximate=False, name=None):
    return execute(lambda a: jax.nn.gelu(a, approximate=approximate), x, _name="gelu")


def swish(x, name=None):
    return execute(jax.nn.silu, x, _name="swish")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    def f(a):
        bx = beta * a
        return jnp.where(bx > threshold, a, jax.nn.softplus(bx) / beta)
    return execute(f, x, _name="softplus")


def softshrink(x, threshold=0.5, name=None):
    return execute(lambda a: jnp.where(a > threshold, a - threshold,
                                       jnp.where(a < -threshold, a + threshold, 0.0)),
                   x, _name="softshrink")


def hardshrink(x, threshold=0.5, name=None):
    return execute(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x, _name="hardshrink")


def tanhshrink(x, name=None):
    return execute(lambda a: a - jnp.tanh(a), x, _name="tanhshrink")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return execute(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x, _name="hardsigmoid")


def hardswish(x, name=None):
    return execute(lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x, _name="hardswish")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return execute(lambda a: jnp.clip(a, min, max), x, _name="hardtanh")


def leaky_relu(x, negative_slope=0.01, name=None):
    return execute(lambda a: jax.nn.leaky_relu(a, negative_slope), x, _name="leaky_relu")


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return execute(lambda a: jnp.where(a > threshold, a, value), x, _name="thresholded_relu")


def softmax(x, axis=-1, dtype=None, name=None):
    from ...framework import dtypes as _dt
    def f(a):
        if dtype is not None:
            a = a.astype(_dt.convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return execute(f, x, _name="softmax")


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._rebind(softmax(x, axis, dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...framework import dtypes as _dt
    def f(a):
        if dtype is not None:
            a = a.astype(_dt.convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return execute(f, x, _name="log_softmax")


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)
    return execute(f, x, weight, _name="prelu")


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    from ...framework.random import next_key
    def f(a):
        if training:
            r = jax.random.uniform(next_key(), a.shape, a.dtype, lower, upper)
        else:
            r = (lower + upper) / 2.0
        return jnp.where(a >= 0, a, r * a)
    return execute(f, x, _name="rrelu")


def maxout(x, groups, axis=1, name=None):
    def f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return execute(f, x, _name="maxout")


def glu(x, axis=-1, name=None):
    def f(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return execute(f, x, _name="glu")


from ...tensor.random import gumbel_softmax  # noqa: F401,E402


def hardtanh_(x, min=-1.0, max=1.0, name=None):
    return x._rebind(hardtanh(x, min, max))


def leaky_relu_(x, negative_slope=0.01, name=None):
    return x._rebind(leaky_relu(x, negative_slope))


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None):
    return x._rebind(thresholded_relu(x, threshold, value))
