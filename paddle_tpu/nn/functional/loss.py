"""Loss functionals. reference: python/paddle/nn/functional/loss.py."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, execute

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "multi_label_soft_margin_loss",
    "soft_margin_loss", "poisson_nll_loss", "gaussian_nll_loss", "ctc_loss",
    "log_loss", "square_error_cost", "sigmoid_focal_loss", "dice_loss",
    "npair_loss", "mse_loss",
]


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    """reference: python/paddle/nn/functional/loss.py:cross_entropy.
    Computed in float32 via log_softmax for numeric parity with the fused
    c_softmax_with_cross_entropy kernels."""
    def f(logits, lab, *rest):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis) if use_softmax \
            else jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
        n_classes = logits.shape[axis]
        if soft_label or (lab.ndim == logits.ndim and lab.shape[axis] == n_classes
                          and jnp.issubdtype(lab.dtype, jnp.floating)):
            tgt = lab.astype(jnp.float32)
            if label_smoothing > 0:
                tgt = (1 - label_smoothing) * tgt + label_smoothing / n_classes
            loss = -jnp.sum(tgt * lp, axis=axis)
            valid = jnp.ones_like(loss, dtype=jnp.bool_)
        else:
            idx = lab
            if idx.ndim == logits.ndim:
                idx = jnp.squeeze(idx, axis)
            idx = idx.astype(jnp.int32)
            valid = idx != ignore_index
            safe = jnp.where(valid, idx, 0)
            picked = jnp.take_along_axis(lp, safe[..., None] if axis in (-1, logits.ndim - 1)
                                         else jnp.expand_dims(safe, axis), axis=axis)
            picked = jnp.squeeze(picked, axis)
            if label_smoothing > 0:
                smooth = -jnp.mean(lp, axis=axis)
                loss = (1 - label_smoothing) * (-picked) + label_smoothing * smooth
            else:
                loss = -picked
            if rest:  # class weights
                w = rest[0]
                loss = loss * jnp.take(w, safe)
            loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            if rest and not soft_label:
                w = rest[0]
                idx = lab
                if idx.ndim == logits.ndim:
                    idx = jnp.squeeze(idx, axis)
                safe = jnp.where(valid, idx.astype(jnp.int32), 0)
                denom = jnp.maximum(jnp.sum(jnp.where(valid, jnp.take(w, safe), 0.0)), 1e-9)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return execute(f, *args, _name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # paddle keeps the reduced axis
    from ...tensor.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, l, *rest):
        eps = 1e-12
        v = -(l * jnp.log(jnp.maximum(p, eps)) + (1 - l) * jnp.log(jnp.maximum(1 - p, eps)))
        if rest:
            v = v * rest[0]
        return _reduce(v, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return execute(f, *args, _name="bce")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def f(z, l, *rest):
        i = 0
        if pos_weight is not None:
            pw = rest[i]; i += 1
            log_w = (pw - 1) * l + 1
            v = (1 - l) * z + log_w * (jnp.logaddexp(0.0, -jnp.abs(z)) + jnp.maximum(-z, 0))
        else:
            v = jnp.maximum(z, 0) - z * l + jnp.logaddexp(0.0, -jnp.abs(z))
        if i < len(rest):
            v = v * rest[i]
        return _reduce(v, reduction)
    args = [logit, label] + [p for p in (pos_weight, weight) if p is not None]
    return execute(f, *args, _name="bce_with_logits")


def mse_loss(input, label, reduction="mean", name=None):
    return execute(lambda a, b: _reduce((a - b) ** 2, reduction), input, label, _name="mse_loss")


def square_error_cost(input, label):
    return execute(lambda a, b: (a - b) ** 2, input, label, _name="square_error_cost")


def l1_loss(input, label, reduction="mean", name=None):
    return execute(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label, _name="l1_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def f(lp, l, *rest):
        idx = l.astype(jnp.int32)
        valid = idx != ignore_index
        safe = jnp.where(valid, idx, 0)
        picked = jnp.take_along_axis(lp, safe[..., None] if lp.ndim == l.ndim + 1 else safe, axis=1)
        if lp.ndim == l.ndim + 1:
            picked = jnp.squeeze(picked, 1)
        v = -picked
        w = rest[0] if rest else None
        if w is not None:
            wv = jnp.take(w, safe)
            v = v * wv
        v = jnp.where(valid, v, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.where(valid, jnp.take(w, safe) if w is not None else 1.0, 0.0))
            return jnp.sum(v) / jnp.maximum(denom, 1e-9)
        return _reduce(v, reduction)
    def f2(lp, l, *rest):
        # input shape (N, C, ...) label (N, ...)
        lp_m = jnp.moveaxis(lp, 1, -1)
        idx = l.astype(jnp.int32)
        valid = idx != ignore_index
        safe = jnp.where(valid, idx, 0)
        picked = jnp.take_along_axis(lp_m, safe[..., None], axis=-1)[..., 0]
        v = -picked
        w = rest[0] if rest else None
        if w is not None:
            v = v * jnp.take(w, safe)
        v = jnp.where(valid, v, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.where(valid, jnp.take(w, safe) if w is not None else jnp.ones_like(v), 0.0))
            return jnp.sum(v) / jnp.maximum(denom, 1e-9)
        return _reduce(v, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return execute(f2, *args, _name="nll_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        v = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # paddle huber form: 0.5*d^2 if d<delta else delta*(d-0.5*delta); uses delta=1.0
        v = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(v, reduction)
    return execute(f, input, label, _name="smooth_l1_loss")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(lp, t):
        if log_target:
            v = jnp.exp(t) * (t - lp)
        else:
            v = t * (jnp.log(jnp.maximum(t, 1e-12)) - lp)
        if reduction == "batchmean":
            return jnp.sum(v) / lp.shape[0]
        return _reduce(v, reduction)
    return execute(f, input, label, _name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return execute(lambda a, b, l: _reduce(jnp.maximum(0.0, -l * (a - b) + margin), reduction),
                   input, other, label, _name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return execute(lambda a, l: _reduce(jnp.where(l == 1, a, jnp.maximum(0.0, margin - a)), reduction),
                   input, label, _name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, l):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        v = jnp.where(l == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(v, reduction)
    return execute(f, input1, input2, label, _name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return execute(f, input, positive, negative, _name="triplet_margin_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        dn2 = distance_function(positive, negative)
        from ...tensor.math import minimum
        dn = minimum(dn, dn2)
    return execute(lambda a, b: _reduce(jnp.maximum(0.0, a - b + margin), reduction),
                   dp, dn, _name="triplet_margin_with_distance_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    def f(z, l, *rest):
        v = -(l * jax.nn.log_sigmoid(z) + (1 - l) * jax.nn.log_sigmoid(-z))
        if rest:
            v = v * rest[0]
        return _reduce(jnp.mean(v, axis=-1), reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return execute(f, *args, _name="multi_label_soft_margin_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    return execute(lambda z, l: _reduce(jnp.log1p(jnp.exp(-l * z)), reduction),
                   input, label, _name="soft_margin_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def f(z, t):
        if log_input:
            v = jnp.exp(z) - t * z
        else:
            v = z - t * jnp.log(z + epsilon)
        if full:
            stirling = t * jnp.log(t) - t + 0.5 * jnp.log(2 * jnp.pi * t)
            v = v + jnp.where(t > 1, stirling, 0.0)
        return _reduce(v, reduction)
    return execute(f, input, label, _name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def f(mu, t, var):
        var = jnp.maximum(var, epsilon)
        v = 0.5 * (jnp.log(var) + (t - mu) ** 2 / var)
        if full:
            v = v + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi, mu.dtype))
        return _reduce(v, reduction)
    return execute(f, input, label, variance, _name="gaussian_nll_loss")


def log_loss(input, label, epsilon=1e-4, name=None):
    return execute(lambda p, l: -l * jnp.log(p + epsilon) - (1 - l) * jnp.log(1 - p + epsilon),
                   input, label, _name="log_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, l, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * l + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * l + (1 - p) * (1 - l)
        mod = (1 - p_t) ** gamma
        a_t = alpha * l + (1 - alpha) * (1 - l)
        v = a_t * mod * ce
        if rest:
            v = v / rest[0]
        return _reduce(v, reduction)
    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return execute(f, *args, _name="sigmoid_focal_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    def f(p, l):
        l_oh = jax.nn.one_hot(l[..., 0] if l.shape[-1] == 1 else l, p.shape[-1], dtype=p.dtype)
        inter = jnp.sum(p * l_oh, axis=tuple(range(1, p.ndim)))
        union = jnp.sum(p, axis=tuple(range(1, p.ndim))) + jnp.sum(l_oh, axis=tuple(range(1, p.ndim)))
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return execute(f, input, label, _name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def f(a, p, l):
        sim = a @ p.T
        lab = (l[:, None] == l[None, :]).astype(sim.dtype)
        lab = lab / jnp.sum(lab, -1, keepdims=True)
        ce = -jnp.sum(lab * jax.nn.log_softmax(sim, -1), -1)
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, -1)) + jnp.mean(jnp.sum(p * p, -1))) * 0.25
        return jnp.mean(ce) + reg * 2
    return execute(f, anchor, positive, labels, _name="npair_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via dynamic-programming in lax.scan (reference: warpctc third_party dep)."""
    def f(lp, lab, in_len, lab_len):
        # lp: (T, N, C) paddle layout
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), -1)
        T, N, C = lp.shape
        S = lab.shape[1]
        # extended labels with blanks: length 2S+1
        ext = jnp.full((N, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        neg_inf = jnp.float32(-1e30)
        alpha0 = jnp.full((N, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lab = lp[0][jnp.arange(N), ext[:, 1]]
        alpha0 = alpha0.at[:, 1].set(first_lab)

        allow_skip = jnp.concatenate([
            jnp.zeros((N, 2), dtype=jnp.bool_),
            (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2])], axis=1)

        def step(alpha, lp_t):
            a_prev = alpha
            a_shift1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], 1)
            a_shift2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], 1)
            a_shift2 = jnp.where(allow_skip, a_shift2, neg_inf)
            m = jnp.maximum(jnp.maximum(a_prev, a_shift1), a_shift2)
            new = m + jnp.log(jnp.exp(a_prev - m) + jnp.exp(a_shift1 - m) + jnp.exp(a_shift2 - m))
            emit = lp_t[jnp.arange(N)[:, None], ext]
            new = new + emit
            return new, new

        _, alphas = jax.lax.scan(step, alpha0, lp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], 0)  # (T, N, 2S+1)
        t_idx = (in_len.astype(jnp.int32) - 1)
        last = alphas[t_idx, jnp.arange(N)]  # (N, 2S+1)
        end1 = 2 * lab_len.astype(jnp.int32)
        end2 = 2 * lab_len.astype(jnp.int32) - 1
        v1 = last[jnp.arange(N), end1]
        v2 = last[jnp.arange(N), jnp.maximum(end2, 0)]
        m = jnp.maximum(v1, v2)
        ll = m + jnp.log(jnp.exp(v1 - m) + jnp.exp(v2 - m))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)
    return execute(f, log_probs, labels, input_lengths, label_lengths, _name="ctc_loss")
