"""paddle.nn.functional surface. reference: python/paddle/nn/functional/__init__.py."""

from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .attention import (  # noqa: F401
    scaled_dot_product_attention,
    flash_attention as _flash_attention_full,
    flash_attn_unpadded,
    sdp_kernel,
)
from .common import flash_attention  # noqa: F401

from ...tensor.manipulation import pad  # noqa: F401
from ...tensor.creation import one_hot  # noqa: F401


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    import jax.numpy as jnp
    from ...framework import dtypes as _dt
    from ...framework.core import execute
    import numpy as np
    if maxlen is None:
        maxlen = int(np.asarray(x._data).max())
    def f(a):
        r = jnp.arange(maxlen)
        return (r[None, :] < a[..., None]).astype(_dt.convert_dtype(dtype))
    return execute(f, x, _name="sequence_mask")
