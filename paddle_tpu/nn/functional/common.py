"""Common functionals: linear, dropout, embedding, padding, similarity.

reference: python/paddle/nn/functional/common.py, input.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, execute
from ...framework.random import next_key

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "embedding", "one_hot", "pad", "zeropad2d", "cosine_similarity",
    "normalize", "label_smooth", "unfold", "fold", "interpolate", "upsample",
    "pixel_shuffle", "pixel_unshuffle", "channel_shuffle", "flash_attention",
    "bilinear",
]

from ...tensor.manipulation import pad  # noqa: F401
from ...tensor.creation import one_hot  # noqa: F401


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b, W shaped (in, out) per paddle convention.
    reference: python/paddle/nn/functional/common.py:linear → the MXU workhorse."""
    if bias is None:
        return execute(lambda a, w: a @ w, x, weight, _name="linear")
    return execute(lambda a, w, b: a @ w + b, x, weight, bias, _name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = next_key()
    def f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return execute(f, x, _name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = next_key()
    def f(a):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)
    return execute(f, x, _name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """reference: python/paddle/nn/functional/input.py:embedding; TP variant
    in distributed VocabParallelEmbedding."""
    def f(idx, w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return execute(f, x, weight, _name="embedding")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return jnp.sum(a * b, axis=axis) / jnp.maximum(na * nb, eps)
    return execute(f, x1, x2, _name="cosine_similarity")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        n = jnp.linalg.norm(a, p, axis=axis, keepdims=True)
        return a / jnp.maximum(n, epsilon)
    return execute(f, x, _name="normalize")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(l, *rest):
        k = l.shape[-1]
        if prior_dist is not None:
            return (1 - epsilon) * l + epsilon * rest[0]
        return (1 - epsilon) * l + epsilon / k
    args = [label] + ([prior_dist] if prior_dist is not None else [])
    return execute(f, *args, _name="label_smooth")


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    args = [x1, x2, weight] + ([bias] if bias is not None else [])
    return execute(f, *args, _name="bilinear")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col. reference: phi/kernels/funcs/im2col.h"""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    p = paddings
    if isinstance(p, int):
        pt = pb = pl = pr = p
    elif len(p) == 2:
        pt = pb = p[0]; pl = pr = p[1]
    else:
        pt, pl, pb, pr = p
    def f(a):
        n, c, h, w = a.shape
        a2 = jnp.pad(a, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
        oh = (h + pt + pb - (dh * (kh - 1) + 1)) // sh + 1
        ow = (w + pl + pr - (dw * (kw - 1) + 1)) // sw + 1
        patches = []
        for i in range(kh):
            for j in range(kw):
                sl = a2[:, :, i * dh:i * dh + (oh - 1) * sh + 1:sh,
                          j * dw:j * dw + (ow - 1) * sw + 1:sw]
                patches.append(sl)
        col = jnp.stack(patches, 2)  # n, c, kh*kw, oh, ow
        return col.reshape(n, c * kh * kw, oh * ow)
    return execute(f, x, _name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    p = paddings
    if isinstance(p, int):
        pt = pb = pl = pr = p
    elif len(p) == 2:
        pt = pb = p[0]; pl = pr = p[1]
    else:
        pt, pl, pb, pr = p
    def f(a):
        n, ckk, L = a.shape
        c = ckk // (kh * kw)
        lh = (oh + pt + pb - (dh * (kh - 1) + 1)) // sh + 1
        lw = (ow + pl + pr - (dw * (kw - 1) + 1)) // sw + 1
        col = a.reshape(n, c, kh, kw, lh, lw)
        out = jnp.zeros((n, c, oh + pt + pb, ow + pl + pr), a.dtype)
        for i in range(kh):
            for j in range(kw):
                out = out.at[:, :, i * dh:i * dh + (lh - 1) * sh + 1:sh,
                             j * dw:j * dw + (lw - 1) * sw + 1:sw].add(col[:, :, i, j])
        return out[:, :, pt:pt + oh, pl:pl + ow]
    return execute(f, x, _name="fold")


# ---------------------------------------------------------------------------
# interpolate / pixel shuffle (reference: nn/functional/vision.py, common.py)
# ---------------------------------------------------------------------------


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    def f(a):
        is_nchw = data_format in ("NCHW", "NCL", "NCDHW")
        spatial_ndim = a.ndim - 2
        if is_nchw:
            spatial = a.shape[2:]
        else:
            spatial = a.shape[1:-1]
        if size is not None:
            out_size = [int(s._data) if isinstance(s, Tensor) else int(s) for s in (size if isinstance(size, (list, tuple)) else [size])]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * spatial_ndim
            out_size = [int(s * f_) for s, f_ in zip(spatial, sf)]
        jmode = {"nearest": "nearest", "bilinear": "bilinear", "trilinear": "trilinear",
                 "bicubic": "bicubic", "linear": "linear", "area": "linear"}[mode]
        if is_nchw:
            new_shape = a.shape[:2] + tuple(out_size)
        else:
            new_shape = (a.shape[0],) + tuple(out_size) + (a.shape[-1],)
        if jmode == "nearest":
            return jax.image.resize(a, new_shape, method="nearest")
        if align_corners:
            # jax.image.resize has no align_corners; emulate with explicit gather
            return _resize_align_corners(a, new_shape, jmode, is_nchw)
        return jax.image.resize(a, new_shape, method=jmode)
    return execute(f, x, _name="interpolate")


def _resize_align_corners(a, new_shape, method, is_nchw):
    # linear interp with corner alignment per spatial dim
    sp_axes = list(range(2, a.ndim)) if is_nchw else list(range(1, a.ndim - 1))
    out = a
    for ax in sp_axes:
        n_in = out.shape[ax]
        n_out = new_shape[ax]
        if n_in == n_out:
            continue
        if n_out == 1 or n_in == 1:
            idx = jnp.zeros((n_out,), jnp.float32)
        else:
            idx = jnp.arange(n_out) * (n_in - 1) / (n_out - 1)
        lo = jnp.floor(idx).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, n_in - 1)
        w = (idx - lo).astype(out.dtype)
        shape = [1] * out.ndim
        shape[ax] = n_out
        w = w.reshape(shape)
        out = jnp.take(out, lo, axis=ax) * (1 - w) + jnp.take(out, hi, axis=ax) * w
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a2 = a.reshape(n, c // (r * r), r, r, h, w)
            a2 = a2.transpose(0, 1, 4, 2, 5, 3)
            return a2.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a2 = a.reshape(n, h, w, r, r, c // (r * r))
        a2 = a2.transpose(0, 1, 3, 2, 4, 5)
        return a2.reshape(n, h * r, w * r, c // (r * r))
    return execute(f, x, _name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor
    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a2 = a.reshape(n, c, h // r, r, w // r, r)
            a2 = a2.transpose(0, 1, 3, 5, 2, 4)
            return a2.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a2 = a.reshape(n, h // r, r, w // r, r, c)
        a2 = a2.transpose(0, 1, 3, 2, 4, 5)
        return a2.reshape(n, h // r, w // r, c * r * r)
    return execute(f, x, _name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            return a.reshape(n, groups, c // groups, h, w).transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = a.shape
        return a.reshape(n, h, w, groups, c // groups).transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)
    return execute(f, x, _name="channel_shuffle")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, training=True, name=None):
    """API-parity alias; implementation in nn/functional/attention.py."""
    from .attention import scaled_dot_product_attention
    out = scaled_dot_product_attention(query, key, value, is_causal=causal,
                                       dropout_p=dropout if training else 0.0)
    if return_softmax:
        return out, None
    return out, None
