"""Attention functionals — the TPU hot path.

reference: python/paddle/nn/functional/flash_attention.py:195 flash_attention,
:976 scaled_dot_product_attention; kernel paddle/phi/kernels/gpu/flash_attn_kernel.cu
(FlashAttention-2 via dynload).

TPU-native design: default is an XLA attention that computes in fp32 with
bf16 inputs (XLA already fuses QK^T→softmax→PV well at moderate sequence
lengths); for long sequences a Pallas flash-attention kernel
(paddle_tpu/ops/pallas/flash_attention.py) is selected via
FLAGS_flash_attention_backend=auto when shapes qualify.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework import flags as _flags
from ...framework.core import Tensor, execute
from ...framework.random import next_key

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "flash_attn_unpadded", "sdp_kernel"]


def _xla_attention(q, k, v, bias=None, causal=False, scale=None, dropout_p=0.0,
                   dropout_key=None):
    # q,k,v: (batch, seq, heads, head_dim) — paddle flash_attention layout
    hd = q.shape[-1]
    s = scale if scale is not None else 1.0 / (hd ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * s
    if bias is not None:
        logits = logits + bias.astype(logits.dtype)
    if causal:
        ql, kl = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((ql, kl), dtype=jnp.bool_), k=kl - ql)
        logits = jnp.where(mask, logits, jnp.float32(-1e30))
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _expand_kv(k, v, num_heads):
    """GQA: broadcast kv heads up to num_heads for the dense path (the
    Pallas kernel consumes the unexpanded heads natively)."""
    kvh = k.shape[2]
    if kvh == num_heads:
        return k, v
    rep = num_heads // kvh

    def expand(a):
        bs, sk, _, d = a.shape
        return jnp.broadcast_to(
            a[:, :, :, None, :], (bs, sk, kvh, rep, d)
        ).reshape(bs, sk, num_heads, d)

    return expand(k), expand(v)


def _use_pallas(q_shape, head_dim, has_bias, dtype=None, causal=True):
    if has_bias:
        # the pallas kernel takes no bias/mask — never select it silently
        return False
    backend = _flags.flag_value("flash_attention_backend")
    if backend == "xla":
        return False
    try:
        import jax.experimental.pallas  # noqa: F401
    except Exception:
        return False
    if jax.default_backend() != "tpu":
        return False
    if backend == "pallas":
        return True
    # auto: per-shape routed choice from the baked hardware ledger
    # (ops/pallas/attention_router) — the r5 A/B showed the flash kernel
    # losing to dense XLA at most production shapes and winning at
    # others, so a fixed seq/head_dim threshold is wrong in both
    # directions. The router falls back to measurement, then to the old
    # thresholds, each with provenance.
    from ...ops.pallas.attention_router import route
    b, seq = q_shape[0], q_shape[1]
    heads = q_shape[2] if len(q_shape) > 3 else 1
    dec = route(b * heads, seq, seq, head_dim,
                dtype if dtype is not None else "bfloat16", causal)
    return dec.fwd == "pallas"


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """paddle layout: (batch, seq, num_heads, head_dim)."""
    dropout_key = next_key() if (dropout_p > 0.0 and training) else None
    use_pallas = _use_pallas(tuple(query.shape), query.shape[-1],
                             attn_mask is not None,
                             dtype=getattr(query, "dtype", None),
                             causal=is_causal) and dropout_key is None

    if use_pallas:
        from ...ops.pallas.flash_attention import flash_attention_bshd
        args = [query, key, value]
        def f(q, k, v):
            # GQA-native: unexpanded kv heads go straight to the kernel
            return flash_attention_bshd(q, k, v, causal=is_causal)

        def f_dense(q, k, v):
            # mathematically-equal dense recompute, differentiable at any
            # order — recorded as the node's higher-order forward so
            # create_graph=True works through the flash path (the Pallas
            # bwd kernels are custom_vjp and stop at first order)
            k, v = _expand_kv(k, v, q.shape[2])
            return _xla_attention(q, k, v, causal=is_causal)

        return execute(f, *args, _name="flash_attention_pallas",
                       _ho_fwd=f_dense)

    args = [query, key, value] + ([attn_mask] if attn_mask is not None else [])

    def f(q, k, v, *rest):
        bias = rest[0] if rest else None
        # GQA on the dense path: expand inside the traced fn
        k, v = _expand_kv(k, v, q.shape[2])
        return _xla_attention(q, k, v, bias=bias, causal=is_causal,
                              dropout_p=dropout_p if training else 0.0,
                              dropout_key=dropout_key)

    return execute(f, *args, _name="scaled_dot_product_attention")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, *, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    out = scaled_dot_product_attention(query, key, value, dropout_p=dropout,
                                       is_causal=causal, training=training)
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False, *,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen (packed/ragged) attention.

    reference: python/paddle/nn/functional/flash_attention.py
    flash_attn_unpadded (varlen FlashAttention-2 over cu_seqlens).

    TPU design: XLA wants static shapes, so the packed (total_tokens,
    heads, dim) layout is gathered into a padded (batch, max_seqlen, ...)
    batch using the static max_seqlen_q/k, attention runs once batched
    with a per-sequence length mask (O(batch * max_len^2) memory, not
    O(total^2)), and results scatter back to the packed layout.
    cu_seqlens_*: (batch+1,) int32 prefix sums.
    """
    dropout_p = dropout if training else 0.0
    dropout_key = next_key() if dropout_p > 0.0 else None
    mq, mk = int(max_seqlen_q), int(max_seqlen_k)

    def f(q, k, v, cq, ck):
        tq = q.shape[0]
        tk = k.shape[0]
        len_q = cq[1:] - cq[:-1]                       # (nb,)
        len_k = ck[1:] - ck[:-1]
        iq = cq[:-1, None] + jnp.arange(mq)[None]      # (nb, mq)
        ik = ck[:-1, None] + jnp.arange(mk)[None]
        valid_q = jnp.arange(mq)[None] < len_q[:, None]
        valid_k = jnp.arange(mk)[None] < len_k[:, None]
        qb = q[jnp.clip(iq, 0, tq - 1)]                # (nb, mq, h, d)
        kb = k[jnp.clip(ik, 0, tk - 1)]
        vb = v[jnp.clip(ik, 0, tk - 1)]
        logits = jnp.einsum("bqhd,bkhd->bhqk", qb, kb,
                            preferred_element_type=jnp.float32) * scale
        mask = valid_q[:, None, :, None] & valid_k[:, None, None, :]
        if causal:
            # bottom-right alignment per sequence (FlashAttention-2 varlen
            # convention, same as the dense reference's tril(k=len_k-len_q)):
            # query i of sequence b sees keys j with i + len_k[b]-len_q[b] >= j
            off = (len_k - len_q)[:, None, None, None]
            mask = mask & (jnp.arange(mq)[:, None] + off
                           >= jnp.arange(mk)[None, :])
        logits = jnp.where(mask, logits, jnp.float32(-1e30))
        probs = jax.nn.softmax(logits, axis=-1)
        probs = jnp.where(mask, probs, 0.0)            # fully-masked pad rows
        if dropout_key is not None:
            keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p,
                                        probs.shape)
            probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
        probs = probs.astype(v.dtype)
        outb = jnp.einsum("bhqk,bkhd->bqhd", probs, vb)
        # scatter back to packed rows; pad rows route out of range and drop
        flat_idx = jnp.where(valid_q, iq, tq).reshape(-1)
        return jnp.zeros_like(q).at[flat_idx].set(
            outb.reshape(-1, *outb.shape[2:]), mode="drop")

    out = execute(f, query, key, value, cu_seqlens_q, cu_seqlens_k,
                  _name="flash_attn_unpadded")
    return out, None


class sdp_kernel:
    """Context manager parity shim (torch-style backend selection)."""

    def __init__(self, enable_flash=True, enable_math=True, enable_mem_efficient=True):
        self.enable_flash = enable_flash

    def __enter__(self):
        self._prev = _flags.flag_value("flash_attention_backend")
        _flags.set_flags({"flash_attention_backend": "pallas" if self.enable_flash else "xla"})
        return self

    def __exit__(self, *exc):
        _flags.set_flags({"flash_attention_backend": self._prev})
        return False
