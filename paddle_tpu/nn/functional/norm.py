"""Normalization functionals. reference: python/paddle/nn/functional/norm.py;
kernels paddle/phi/kernels/gpu/{batch_norm,layer_norm,group_norm}_kernel.cu.

XLA fuses the mean/var/normalize/affine chain into one kernel on TPU;
rms_norm additionally has a Pallas fast path (incubate.nn.functional).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, execute

__all__ = ["batch_norm", "layer_norm", "instance_norm", "group_norm",
           "local_response_norm", "rms_norm"]


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    ch_axis = 1 if data_format.startswith("NC") else -1
    use_stats = (not training) if use_global_stats is None else use_global_stats

    def stats_shape(a):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        return shape

    if use_stats:
        def f(a, rm, rv, *rest):
            shape = stats_shape(a)
            out = (a - rm.reshape(shape)) * jax.lax.rsqrt(rv.reshape(shape) + epsilon)
            i = 0
            if weight is not None:
                out = out * rest[i].reshape(shape); i += 1
            if bias is not None:
                out = out + rest[i].reshape(shape)
            return out.astype(a.dtype)
        args = [x, running_mean, running_var] + [p for p in (weight, bias) if p is not None]
        return execute(f, *args, _name="batch_norm")

    # training: compute batch stats, update running stats in place
    axes = tuple(i for i in range(x.ndim) if i != (ch_axis % x.ndim))

    def f(a, *rest):
        m = jnp.mean(a.astype(jnp.float32), axis=axes)
        v = jnp.var(a.astype(jnp.float32), axis=axes)
        shape = stats_shape(a)
        out = (a - m.reshape(shape).astype(a.dtype)) * jax.lax.rsqrt(
            v.reshape(shape).astype(a.dtype) + epsilon)
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape); i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape)
        return out.astype(a.dtype), m, v

    from ...framework.core import buffer_update

    args = [x] + [p for p in (weight, bias) if p is not None]
    out, batch_mean, batch_var = execute(f, *args, _name="batch_norm")
    if running_mean is not None:
        buffer_update(running_mean,
                      momentum * running_mean._data
                      + (1.0 - momentum) * batch_mean._data.astype(running_mean._data.dtype))
    if running_var is not None:
        n = 1
        for i in axes:
            n *= x.shape[i]
        unbiased = batch_var._data * (n / max(n - 1, 1))
        buffer_update(running_var,
                      momentum * running_var._data
                      + (1.0 - momentum) * unbiased.astype(running_var._data.dtype))
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    nd = len(tuple(normalized_shape))

    def f(a, *rest):
        axes = tuple(range(a.ndim - nd, a.ndim))
        a32 = a.astype(jnp.float32)
        m = jnp.mean(a32, axis=axes, keepdims=True)
        v = jnp.var(a32, axis=axes, keepdims=True)
        out = (a32 - m) * jax.lax.rsqrt(v + epsilon)
        out = out.astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * rest[i]; i += 1
        if bias is not None:
            out = out + rest[i]
        return out

    args = [x] + [p for p in (weight, bias) if p is not None]
    return execute(f, *args, _name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (no mean subtraction) — the Llama-family norm.
    reference: python/paddle/incubate/nn/functional/fused_rms_norm.py"""
    def f(a, *rest):
        a32 = a.astype(jnp.float32)
        ms = jnp.mean(a32 * a32, axis=-1, keepdims=True)
        out = (a32 * jax.lax.rsqrt(ms + epsilon)).astype(a.dtype)
        if rest:
            out = out * rest[0]
        return out
    args = [x] + ([weight] if weight is not None else [])
    return execute(f, *args, _name="rms_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    ch_axis = 1 if data_format.startswith("NC") else -1

    def f(a, *rest):
        axes = tuple(range(2, a.ndim)) if ch_axis == 1 else tuple(range(1, a.ndim - 1))
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + eps)
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape); i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape)
        return out.astype(a.dtype)

    args = [x] + [p for p in (weight, bias) if p is not None]
    return execute(f, *args, _name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    def f(a, *rest):
        if data_format == "NCHW" or a.ndim == 2:
            n, c = a.shape[:2]
            spatial = a.shape[2:]
            g = a.reshape((n, num_groups, c // num_groups) + spatial)
            axes = tuple(range(2, g.ndim))
            m = jnp.mean(g, axis=axes, keepdims=True)
            v = jnp.var(g, axis=axes, keepdims=True)
            out = ((g - m) * jax.lax.rsqrt(v + epsilon)).reshape(a.shape)
            shape = [1] * a.ndim
            shape[1] = c
        else:
            n, c = a.shape[0], a.shape[-1]
            spatial = a.shape[1:-1]
            g = a.reshape((n,) + spatial + (num_groups, c // num_groups))
            axes = tuple(range(1, g.ndim - 2)) + (g.ndim - 1,)
            m = jnp.mean(g, axis=axes, keepdims=True)
            v = jnp.var(g, axis=axes, keepdims=True)
            out = ((g - m) * jax.lax.rsqrt(v + epsilon)).reshape(a.shape)
            shape = [1] * a.ndim
            shape[-1] = c
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape); i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape)
        return out.astype(a.dtype)

    args = [x] + [p for p in (weight, bias) if p is not None]
    return execute(f, *args, _name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def f(a):
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        sq = a * a
        moved = jnp.moveaxis(sq, ch_axis, -1)
        pad_lo = (size - 1) // 2
        pad_hi = size - 1 - pad_lo
        padded = jnp.pad(moved, [(0, 0)] * (moved.ndim - 1) + [(pad_lo, pad_hi)])
        win = jnp.stack([padded[..., i:i + moved.shape[-1]] for i in range(size)], -1)
        s = jnp.sum(win, -1)
        s = jnp.moveaxis(s, -1, ch_axis)
        div = (k + alpha * s) ** beta
        return a / div
    return execute(f, x, _name="local_response_norm")
