"""Convolutions via lax.conv_general_dilated — XLA tiles these onto the MXU.

reference: python/paddle/nn/functional/conv.py; kernels
paddle/phi/kernels/gpu/conv_kernel.cu + gpudnn. One general primitive
replaces the whole cuDNN algo-selection + autotune machinery
(paddle/phi/kernels/autotune/) — XLA picks the conv algorithm.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import execute

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _padding(padding, n, data_format):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    # paddle also allows [[0,0],[0,0],[h0,h1],[w0,w1]] style
    if len(padding) == n + 2:
        sp = padding[2:] if data_format.startswith("NC") else padding[1:-1]
        return [(int(p[0]), int(p[1])) if isinstance(p, (list, tuple)) else (int(p), int(p)) for p in sp]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, n):
    sd = _tuple(stride, n)
    dd = _tuple(dilation, n)
    pad = _padding(padding, n, data_format)
    if data_format in ("NCHW", "NCL", "NCDHW"):
        lhs_spec = "NC" + "DHW"[3 - n:]
    else:
        lhs_spec = "N" + "DHW"[3 - n:] + "C"
    rhs_spec = "OI" + "DHW"[3 - n:]
    out_spec = lhs_spec
    dn = jax.lax.conv_dimension_numbers((1,) * (n + 2), (1,) * (n + 2),
                                        (lhs_spec, rhs_spec, out_spec))

    def f(a, w, *rest):
        # No preferred_element_type=f32 here: the MXU accumulates bf16
        # convs in f32 regardless, and jax's conv transpose rule can't
        # handle the widened cotangent (f32 cotangent x bf16 weight)
        # under grad — it raised a dtype mismatch in the bf16 train step.
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=sd, padding=pad,
            rhs_dilation=dd, dimension_numbers=dn,
            feature_group_count=groups,
        )
        out = out.astype(a.dtype)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            ci = 1 if lhs_spec.startswith("NC") else out.ndim - 1
            shape[ci] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    args = [x, weight] + ([bias] if bias is not None else [])
    return execute(f, *args, _name=f"conv{n}d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, data_format, n, output_size=None):
    sd = _tuple(stride, n)
    dd = _tuple(dilation, n)
    opad = _tuple(output_padding, n) if output_padding is not None else (0,) * n
    same_pad = False
    if isinstance(padding, str):
        up = padding.upper()
        if up == "VALID":
            pad = [(0, 0)] * n
        elif up == "SAME":
            # paddle SAME for transpose conv: output = input * stride;
            # total pad per dim = k_eff - stride (clamped), split low/high
            same_pad = True
            pad = None  # derived from the kernel size inside f
        else:
            raise ValueError(f"padding must be SAME/VALID or ints, got "
                             f"{padding!r}")
    else:
        pad = _padding(padding, n, data_format)
    if data_format in ("NCHW", "NCL", "NCDHW"):
        lhs_spec = "NC" + "DHW"[3 - n:]
    else:
        lhs_spec = "N" + "DHW"[3 - n:] + "C"
    # paddle transpose-conv weight layout: (in, out/groups, *k)
    rhs_spec = "IO" + "DHW"[3 - n:]
    dn = jax.lax.conv_dimension_numbers((1,) * (n + 2), (1,) * (n + 2),
                                        (lhs_spec, rhs_spec, lhs_spec))

    def f(a, w, *rest):
        # grad-of-conv formulation: lhs_dilation = stride
        k_eff = [dd[i] * (w.shape[2 + i] - 1) + 1 for i in range(n)]
        if same_pad:
            # out = in * stride exactly: when k_eff < stride the deficit
            # goes NEGATIVE on the high side, which EXTENDS tpad below
            totals = [k_eff[i] - sd[i] for i in range(n)]
            pads = [(max(t, 0) // 2, t - max(t, 0) // 2) for t in totals]
        else:
            pads = pad
        tpad = [(k_eff[i] - 1 - pads[i][0],
                 k_eff[i] - 1 - pads[i][1] + opad[i])
                for i in range(n)]
        if output_size is not None:
            # paddle contract: output_size picks the exact inverse-conv
            # size within [default, default + stride) — realized by
            # extending the high-side transpose pad (values there are real
            # conv outputs over the dilated input border, not zero fill) —
            # and is mutually exclusive with output_padding
            if any(o != 0 for o in opad):
                raise ValueError(
                    "output_padding must not be set when output_size is "
                    "specified")
            osz = output_size if isinstance(output_size, (list, tuple)) \
                else (output_size,) * n
            sp0 = 2 if lhs_spec.startswith("NC") else 1
            for i in range(n):
                cur = ((a.shape[sp0 + i] - 1) * sd[i] + 1 + tpad[i][0]
                       + tpad[i][1] - (k_eff[i] - 1))
                extra = int(osz[i]) - cur
                if not (0 <= extra < max(sd[i], 1)):
                    raise ValueError(
                        f"output_size[{i}]={osz[i]} not reachable: valid "
                        f"range [{cur}, {cur + max(sd[i], 1)})")
                tpad[i] = (tpad[i][0], tpad[i][1] + extra)
        if groups > 1:
            # grouped transpose: split and concat along channel axis
            ci = 1 if lhs_spec.startswith("NC") else a.ndim - 1
            a_groups = jnp.split(a, groups, axis=ci)
            w_groups = jnp.split(w, groups, axis=0)
            outs = []
            for ag, wg in zip(a_groups, w_groups):
                wf = jnp.flip(wg, axis=tuple(range(2, 2 + n)))
                wf = jnp.swapaxes(wf, 0, 1)  # -> (out, in, *k) as OI
                dn2 = jax.lax.conv_dimension_numbers(
                    (1,) * (n + 2), (1,) * (n + 2),
                    (lhs_spec, "OI" + "DHW"[3 - n:], lhs_spec))
                outs.append(jax.lax.conv_general_dilated(
                    ag, wf, window_strides=(1,) * n, padding=tpad,
                    lhs_dilation=sd, rhs_dilation=dd, dimension_numbers=dn2))
            out = jnp.concatenate(outs, axis=ci)
        else:
            wf = jnp.flip(w, axis=tuple(range(2, 2 + n)))
            wf = jnp.swapaxes(wf, 0, 1)
            dn2 = jax.lax.conv_dimension_numbers(
                (1,) * (n + 2), (1,) * (n + 2),
                (lhs_spec, "OI" + "DHW"[3 - n:], lhs_spec))
            out = jax.lax.conv_general_dilated(
                a, wf, window_strides=(1,) * n, padding=tpad,
                lhs_dilation=sd, rhs_dilation=dd, dimension_numbers=dn2)
        out = out.astype(a.dtype)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            ci = 1 if lhs_spec.startswith("NC") else out.ndim - 1
            shape[ci] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    args = [x, weight] + ([bias] if bias is not None else [])
    return execute(f, *args, _name=f"conv{n}d_transpose")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 1, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 2, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 3, output_size)
