"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

reference: python/paddle/nn/decode.py — BeamSearchDecoder (beam expansion,
length-ordered scores, finished handling) and dynamic_decode (the step loop
with early stop).

TPU design: each decode step is fixed-shape tensor math (topk over
beam*vocab, gathers by parent beam); the step loop runs eagerly (host) with
early stop, matching the reference's dynamic control flow — a lax.while_loop
compiled variant drops in later without changing this API. Back-tracing
uses functional.gather_tree.
"""

from __future__ import annotations

from collections import namedtuple

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["BeamSearchDecoder", "dynamic_decode"]

BeamSearchOutput = namedtuple("BeamSearchOutput",
                              ["predicted_ids", "parent_ids", "scores"])


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _tile_beam(tree, beam):
    def one(a):
        a = _arr(a)
        return jnp.repeat(a, beam, axis=0)  # (B, ...) -> (B*beam, ...)
    return jax.tree_util.tree_map(one, tree,
                                  is_leaf=lambda v: isinstance(v, Tensor))


class BeamSearchDecoder:
    """reference: nn/decode.py BeamSearchDecoder.

    cell(step_input, states) -> (cell_output, next_states); embedding_fn
    maps token ids to step inputs; output_fn maps cell outputs to vocab
    logits (None if the cell already emits logits)."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """(B, ...) -> (B*beam, ...) by repetition (reference helper)."""
        return Tensor(jnp.repeat(_arr(x), beam_size, axis=0))

    def initialize(self, initial_cell_states):
        beam = self.beam_size
        states = _tile_beam(initial_cell_states, beam)
        leaf = jax.tree_util.tree_leaves(states)[0]
        bsz = leaf.shape[0] // beam
        ids = jnp.full((bsz * beam,), self.start_token, jnp.int32)
        # only beam 0 is live initially; others start at -inf so the first
        # topk doesn't pick duplicate roots
        log_probs = jnp.full((bsz, beam), -1e30, jnp.float32).at[:, 0].set(0)
        finished = jnp.zeros((bsz, beam), jnp.bool_)
        return ids, states, log_probs, finished

    def step(self, ids, states, log_probs, finished):
        beam = self.beam_size
        bsz = log_probs.shape[0]
        step_in = Tensor(ids)
        if self.embedding_fn is not None:
            step_in = self.embedding_fn(step_in)
        out, next_states = self.cell(step_in, states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        logits = _arr(out)
        v = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        step_lp = step_lp.reshape(bsz, beam, v)
        # finished beams only extend with end_token at zero cost
        fin_mask = jnp.full((v,), -1e30).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[..., None], fin_mask[None, None],
                            step_lp)
        total = log_probs[..., None] + step_lp          # (B, beam, V)
        top_val, top_idx = jax.lax.top_k(total.reshape(bsz, beam * v), beam)
        parent = (top_idx // v).astype(jnp.int32)       # (B, beam)
        token = (top_idx % v).astype(jnp.int32)
        # gather states by parent beam
        flat_parent = (jnp.arange(bsz)[:, None] * beam + parent).reshape(-1)

        def pick(a):
            return _arr(a)[flat_parent]
        next_states = jax.tree_util.tree_map(
            pick, next_states, is_leaf=lambda x: isinstance(x, Tensor))
        new_finished = jnp.take_along_axis(finished, parent, 1) | \
            (token == self.end_token)
        return (token.reshape(-1), next_states, top_val, new_finished,
                token, parent)


def dynamic_decode(decoder, inits=None, max_step_num=100, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=True,
                   **kwargs):
    """Run decoder steps until every beam finishes or max_step_num.
    reference: nn/decode.py dynamic_decode. Returns
    (BeamSearchOutput, final_states, sequence_lengths)."""
    ids, states, log_probs, finished = decoder.initialize(inits)
    bsz, beam = log_probs.shape
    tokens_t = []
    parents_t = []
    lengths = jnp.zeros((bsz, beam), jnp.int32)
    for _ in range(int(max_step_num)):
        (ids, states, log_probs, finished, token,
         parent) = decoder.step(ids, states, log_probs, finished)
        tokens_t.append(token)
        parents_t.append(parent)
        lengths = lengths + (~finished).astype(jnp.int32)
        if bool(jnp.all(finished)):
            break
    ids_arr = jnp.stack(tokens_t)                      # (T, B, beam)
    parents_arr = jnp.stack(parents_t)
    from .functional.extras import gather_tree
    full = gather_tree(Tensor(ids_arr), Tensor(parents_arr))
    full_arr = _arr(full)
    if not output_time_major:
        full_arr = jnp.moveaxis(full_arr, 0, 1)        # (B, T, beam)
        parents_arr = jnp.moveaxis(parents_arr, 0, 1)  # keep layouts aligned
    out = BeamSearchOutput(predicted_ids=Tensor(full_arr),
                           parent_ids=Tensor(parents_arr),
                           scores=Tensor(log_probs))
    seq_len = Tensor(lengths)
    if return_length:
        return out, states, seq_len
    return out, states
