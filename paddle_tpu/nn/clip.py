"""Gradient clipping. reference: python/paddle/nn/clip.py.

ClipGradByGlobalNorm computes the global norm over all grads in one fused
XLA reduction (under jit) — the reference needs a multi-tensor CUDA kernel
for the same.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor, execute

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, execute(lambda a: jnp.clip(a, self.min, self.max), g,
                                   _name="clip_by_value")))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            def f(a):
                n = jnp.sqrt(jnp.sum(a.astype(jnp.float32) ** 2))
                scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
                return (a * scale).astype(a.dtype)
            out.append((p, execute(f, g, _name="clip_by_norm")))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """reference: python/paddle/nn/clip.py:ClipGradByGlobalNorm; hybrid-parallel
    variant reduces the norm across TP/PP groups
    (fleet HybridParallelClipGrad) — under GSPMD the partial norms of sharded
    grads are combined by XLA automatically."""

    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = clip_norm

    def _clip(self, params_grads):
        grads = [g for _, g in params_grads if g is not None]
        if not grads:
            return params_grads

        def sq(a):
            return jnp.sum(a.astype(jnp.float32) ** 2)

        def f(*arrs):
            total = jnp.asarray(0.0, jnp.float32)
            for a in arrs:
                total = total + sq(a)
            gn = jnp.sqrt(total)
            scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
            return tuple((a * scale).astype(a.dtype) for a in arrs)

        clipped = execute(f, *grads, _name="clip_by_global_norm")
        if not isinstance(clipped, tuple):
            clipped = (clipped,)
        it = iter(clipped)
        out = []
        for p, g in params_grads:
            out.append((p, next(it) if g is not None else None))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    grads = [p.grad for p in params if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    def f(*arrs):
        if norm_type == float("inf"):
            n = jnp.max(jnp.stack([jnp.max(jnp.abs(a)) for a in arrs]))
        else:
            n = jnp.sum(jnp.stack([jnp.sum(jnp.abs(a.astype(jnp.float32)) ** norm_type)
                                   for a in arrs])) ** (1.0 / norm_type)
        scale = jnp.minimum(max_norm / (n + 1e-6), 1.0)
        return (n,) + tuple((a * scale).astype(a.dtype) for a in arrs)
    outs = execute(f, *grads, _name="clip_grad_norm_")
    total = outs[0]
    it = iter(outs[1:])
    for p in params:
        if p.grad is not None:
            p.grad._data = next(it)._data
    return total


def clip_grad_value_(parameters, clip_value):
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    for p in params:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)
