"""paddle.nn surface. reference: python/paddle/nn/__init__.py."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer.layers import Layer, LayerList, ParameterList, Sequential, LayerDict  # noqa: F401
from .layer.common import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .layer.extras import *  # noqa: F401,F403
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
    clip_grad_norm_, clip_grad_value_,
)

from . import utils  # noqa: F401
from . import quant  # noqa: F401
