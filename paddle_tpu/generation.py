"""Compiled autoregressive generation with a dense KV cache.

reference capability: the serving path the reference builds from
block_multihead_attention / masked_multihead_attention fused kernels
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu,
incubate/nn/functional/masked_multihead_attention.py) plus top_p_sampling
(tensor/search.py:1363) — prefill once, then one-token decode steps
against a KV cache.

TPU-native design: the whole generate() is ONE jit per
(batch, prompt_len, max_new_tokens) signature — prefill fills per-layer
K/V caches (static max length, position-masked), then `lax.scan` runs the
decode steps; layer weights are stacked (L, ...) arrays so each decode
step is itself a `lax.scan` over depth (compiled size O(1) in L). Greedy
or sampled (temperature / top-k / top-p) next-token choice happens inside
the scan. The paged-cache variant for many-sequence serving lives in
ops/paged_attention.py; this dense path is the single-program analog of
the reference's masked_multihead_attention decode.

Supports LlamaForCausalLM (flagship) and any causal LM exposing
`model(input_ids) -> logits` through the recompute fallback.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .framework.core import Tensor
from .framework import random as _random
from .observability import span as _span
from .observability.catalog import metric as _metric
from .observability.tracing import get_tracer as _tracer
from .observability.tracing import new_trace_id as _new_trace_id

__all__ = ["generate", "GenerationConfig", "WeightOnlyGenerator"]


class GenerationConfig:
    """reference: the generation knobs of top_p_sampling + sampling loops."""

    def __init__(self, max_new_tokens=32, do_sample=False, temperature=1.0,
                 top_k=0, top_p=1.0, eos_token_id=None):
        self.max_new_tokens = max_new_tokens
        self.do_sample = do_sample
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_token_id = eos_token_id


# ---------------------------------------------------------------------------
# pure llama math over stacked params (mirrors models/llama.py exactly).
# DELIBERATE duplication: the cache-threaded decode step can't reuse the
# module forward (functional_call returns no per-layer K/V). Divergence is
# gated by tests/test_generation.py's exact greedy-parity checks against
# the module forward (incl. GQA + tied-embedding configs) — change the
# model math and those tests fail here.
# ---------------------------------------------------------------------------


def _rms(x, w, eps):
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(ms + eps)).astype(x.dtype) * w


def _rope(x, pos, theta):
    """neox-style rope at absolute positions `pos` (any shape broadcastable
    to x[..., :0]); x: (..., heads, head_dim)."""
    d = x.shape[-1]
    inv = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    freqs = pos[..., None].astype(jnp.float32) * inv      # (..., d/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)        # (..., d)
    s, c = jnp.sin(emb), jnp.cos(emb)
    s = s[..., None, :].astype(x.dtype)                   # add head axis
    c = c[..., None, :].astype(x.dtype)
    x1, x2 = jnp.split(x, 2, axis=-1)
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return x * c + rot * s


def _gqa(a, rep):
    if rep == 1:
        return a
    b, s, hkv, d = a.shape
    return jnp.broadcast_to(a[:, :, :, None, :],
                            (b, s, hkv, rep, d)).reshape(b, s, hkv * rep, d)


def _prefill_flash_routed(bh, s, d, dtype):
    """Prefill attention backend: consult the baked per-shape router
    (same ledger as the train path) — dense XLA wins most v5e prefill
    shapes, flash wins long ones. Dense (False) on non-TPU or any
    router failure."""
    if jax.default_backend() != "tpu":
        return False
    try:
        from .ops.pallas.attention_router import route
        return route(bh, s, s, d, dtype, True).fwd == "pallas"
    except Exception:
        return False


def _llama_layer_prefill(lp, h, pos, cfg):
    """Full-sequence layer forward; returns (h_out, (k, v)) with k/v rotated
    and UNexpanded (kv heads)."""
    eps, theta = cfg["eps"], cfg["theta"]
    nh, nkv, hd = cfg["heads"], cfg["kv_heads"], cfg["head_dim"]
    b, s, _ = h.shape
    x = _rms(h, lp["input_layernorm.weight"], eps)
    q = (x @ lp["self_attn.q_proj.weight"]).reshape(b, s, nh, hd)
    k = (x @ lp["self_attn.k_proj.weight"]).reshape(b, s, nkv, hd)
    v = (x @ lp["self_attn.v_proj.weight"]).reshape(b, s, nkv, hd)
    q = _rope(q, pos, theta)
    k = _rope(k, pos, theta)
    if _prefill_flash_routed(b * nh, s, hd, h.dtype):
        # routed flash prefill: GQA-native (kv stays unexpanded), causal.
        # Every prefill caller passes pos = arange rows, so the pos-based
        # mask below IS the standard causal structure the kernel applies.
        from .ops.pallas.flash_attention import flash_attention_bshd
        attn = flash_attention_bshd(q, k, v, causal=True).reshape(
            b, s, nh * hd)
    else:
        kx, vx = _gqa(k, nh // nkv), _gqa(v, nh // nkv)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, kx,
            preferred_element_type=jnp.float32) / (hd ** 0.5)
        causal = pos[:, :, None] >= pos[:, None, :]       # (b, s, s)
        scores = jnp.where(causal[:, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(vx.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, vx).reshape(
            b, s, nh * hd)
    h = h + attn @ lp["self_attn.o_proj.weight"]
    x = _rms(h, lp["post_attention_layernorm.weight"], eps)
    gate = x @ lp["mlp.gate_proj.weight"]
    up = x @ lp["mlp.up_proj.weight"]
    h = h + (jax.nn.silu(gate) * up) @ lp["mlp.down_proj.weight"]
    return h, (k, v)


def _llama_layer_prefill_chunk(lp, h, kc, vc, table_row, start, cfg,
                               fmt=None, kc_scale=None, vc_scale=None,
                               lora=None):
    """One layer forward over a prompt CHUNK against the paged pool (the
    serving engine's chunked prefill): rotate the chunk's Q/K at absolute
    positions, scatter the chunk's K/V into the pool (multi-token write),
    then attend over every cached position `<=` the query's absolute
    position — previous chunks plus causal-within-chunk in one softmax.

    h: (1, C, H) chunk hidden states; kc/vc: ONE layer's
    (num_blocks, block_size, KVH, D) pool slice; table_row: (max_blocks,)
    block table of the owning sequence; start: absolute position of the
    chunk's first token. Returns (h_out, (kc, vc)) — with a quantized
    `fmt` (and its per-(token, head) scale pool slices) the writes encode
    and the attention read dequantizes in place, and the second element
    becomes (kc, vc, kc_scale, vc_scale). fmt=None keeps the original
    trace byte-for-byte.

    `lora` (round 22, multi-adapter serving): an optional
    (A_q [H, r], B_q [r, Dq], A_v [H, r], B_v [r, Dv]) tuple of this
    layer's already-gathered low-rank factors; the q/v projections gain
    `x @ A @ B` deltas in one batched einsum each. lora=None keeps the
    original trace byte-for-byte (the all-zeros base slot makes
    adapter_id 0 numerically identical even when wired).
    """
    from .ops.paged_attention import (kv_write_chunk,
                                      paged_attention_prefill_chunk,
                                      write_chunk_to_cache)
    eps, theta = cfg["eps"], cfg["theta"]
    nh, nkv, hd = cfg["heads"], cfg["kv_heads"], cfg["head_dim"]
    b, c, _ = h.shape                      # b == 1: one admission at a time
    pos = start + jnp.arange(c)[None]      # (1, C) absolute positions
    x = _rms(h, lp["input_layernorm.weight"], eps)
    q_lin = x @ lp["self_attn.q_proj.weight"]
    v_lin = x @ lp["self_attn.v_proj.weight"]
    if lora is not None:
        a_q, b_q, a_v, b_v = lora
        q_lin = q_lin + jnp.einsum("bch,hr,rd->bcd", x,
                                   a_q.astype(x.dtype),
                                   b_q.astype(x.dtype))
        v_lin = v_lin + jnp.einsum("bch,hr,rd->bcd", x,
                                   a_v.astype(x.dtype),
                                   b_v.astype(x.dtype))
    q = q_lin.reshape(b, c, nh, hd)
    k = (x @ lp["self_attn.k_proj.weight"]).reshape(b, c, nkv, hd)
    v = v_lin.reshape(b, c, nkv, hd)
    q = _rope(q, pos, theta)
    k = _rope(k, pos, theta)
    quant = fmt is not None and fmt.quantized
    if quant:
        kc, vc, kc_scale, vc_scale = kv_write_chunk(
            fmt, kc, vc, kc_scale, vc_scale, k[0], v[0], table_row, start)
    else:
        kc, vc = write_chunk_to_cache(kc, vc, k[0], v[0], table_row, start)
    attn = paged_attention_prefill_chunk(q[0], kc, vc, table_row, start,
                                         scale=1.0 / (hd ** 0.5),
                                         fmt=fmt if quant else None,
                                         k_scale_cache=kc_scale,
                                         v_scale_cache=vc_scale)
    h = h + attn.reshape(b, c, nh * hd) @ lp["self_attn.o_proj.weight"]
    x = _rms(h, lp["post_attention_layernorm.weight"], eps)
    gate = x @ lp["mlp.gate_proj.weight"]
    up = x @ lp["mlp.up_proj.weight"]
    h = h + (jax.nn.silu(gate) * up) @ lp["mlp.down_proj.weight"]
    if quant:
        return h, (kc, vc, kc_scale, vc_scale)
    return h, (kc, vc)


def _llama_layer_decode(lp, h, k_cache, v_cache, t, cfg):
    """One-token layer forward against the cache; h: (b, 1, H). The caches
    hold rotated K / V at positions < t (positions >= t are masked)."""
    eps, theta = cfg["eps"], cfg["theta"]
    nh, nkv, hd = cfg["heads"], cfg["kv_heads"], cfg["head_dim"]
    b = h.shape[0]
    T = k_cache.shape[1]
    x = _rms(h, lp["input_layernorm.weight"], eps)
    q = (x @ lp["self_attn.q_proj.weight"]).reshape(b, 1, nh, hd)
    k = (x @ lp["self_attn.k_proj.weight"]).reshape(b, 1, nkv, hd)
    v = (x @ lp["self_attn.v_proj.weight"]).reshape(b, 1, nkv, hd)
    pos = jnp.full((b, 1), t, jnp.int32)
    q = _rope(q, pos, theta)
    k = _rope(k, pos, theta)
    k_cache = jax.lax.dynamic_update_slice_in_dim(k_cache, k, t, axis=1)
    v_cache = jax.lax.dynamic_update_slice_in_dim(v_cache, v, t, axis=1)
    kx = _gqa(k_cache, nh // nkv)
    vx = _gqa(v_cache, nh // nkv)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, kx,
                        preferred_element_type=jnp.float32) / (hd ** 0.5)
    valid = (jnp.arange(T) <= t)[None, None, None, :]
    scores = jnp.where(valid, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(vx.dtype)
    attn = jnp.einsum("bhqk,bkhd->bqhd", probs, vx).reshape(b, 1, nh * hd)
    h = h + attn @ lp["self_attn.o_proj.weight"]
    x = _rms(h, lp["post_attention_layernorm.weight"], eps)
    gate = x @ lp["mlp.gate_proj.weight"]
    up = x @ lp["mlp.up_proj.weight"]
    h = h + (jax.nn.silu(gate) * up) @ lp["mlp.down_proj.weight"]
    return h, k_cache, v_cache


def _sample(logits, key, gc: GenerationConfig, temperature, top_p):
    """do_sample / top_k / whether-top-p-filters are STRUCTURAL (change the
    program); the temperature and top_p VALUES are traced scalars so knob
    changes within a variant never recompile."""
    if not gc.do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if gc.top_k and gc.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -gc.top_k][..., None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if gc.top_p < 1.0:  # top_p == 1 skips the full-vocab sort entirely
        probs = jax.nn.softmax(logits, axis=-1)
        order = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, order, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        keep_sorted = (cum - sorted_p) < top_p
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(logits.shape[0])[:, None], order].set(keep_sorted)
        logits = jnp.where(keep, logits, -1e30)
    return jax.random.categorical(key, logits, axis=-1)


def _build_llama_generate(config, tied: bool, gc: GenerationConfig):
    """Compile-once decode program. Weights enter as ARGUMENTS (not baked
    constants), so one executable serves the model across optimizer steps /
    set_state_dict and holds no weight copies of its own."""
    cfg = dict(eps=config.rms_norm_eps, theta=config.rope_theta,
               heads=config.num_attention_heads,
               kv_heads=config.num_key_value_heads,
               head_dim=config.hidden_size // config.num_attention_heads)

    def run(stacked, embed_w, norm_w, head_w, input_ids, key, temperature,
            top_p):
        def logits_of(h_last):
            h = _rms(h_last, norm_w, cfg["eps"])
            w = embed_w.T if tied else head_w
            return (h @ w).astype(jnp.float32)

        b, s = input_ids.shape
        total = s + gc.max_new_tokens
        pos = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
        h = jnp.take(embed_w, input_ids, axis=0)

        # ---- prefill: scan over stacked layers, collecting K/V ----------
        def prefill_layer(hh, lp):
            hh, (k, v) = _llama_layer_prefill(lp, hh, pos, cfg)
            return hh, (k, v)

        h, (ks, vs) = jax.lax.scan(prefill_layer, h, stacked)
        # ks: (L, b, s, kvh, hd) -> pad the time axis to `total`
        padt = ((0, 0), (0, 0), (0, gc.max_new_tokens), (0, 0), (0, 0))
        k_cache = jnp.pad(ks, padt)
        v_cache = jnp.pad(vs, padt)

        first_logits = logits_of(h[:, -1])
        key, sub = jax.random.split(key)
        first_tok = _sample(first_logits, sub, gc, temperature, top_p)

        # ---- decode: scan over steps; inner scan over layers ------------
        def step(carry, i):
            tok, kc, vc, key, done = carry
            t = s + i
            hh = jnp.take(embed_w, tok[:, None], axis=0)  # (b, 1, H)

            def dec_layer(hcar, layer_in):
                lp, kl, vl = layer_in
                hh2, kl2, vl2 = _llama_layer_decode(lp, hcar, kl, vl, t, cfg)
                return hh2, (kl2, vl2)

            hh, (kc, vc) = jax.lax.scan(dec_layer, hh, (stacked, kc, vc))
            logits = logits_of(hh[:, -1])
            key, sub = jax.random.split(key)
            nxt = _sample(logits, sub, gc, temperature, top_p)
            if gc.eos_token_id is not None:
                done = done | (tok == gc.eos_token_id)
                nxt = jnp.where(done, gc.eos_token_id, nxt)
            return (nxt, kc, vc, key, done), tok

        done0 = jnp.zeros((b,), bool)
        (last, _, _, _, _), toks = jax.lax.scan(
            step, (first_tok, k_cache, v_cache, key, done0),
            jnp.arange(gc.max_new_tokens - 1))
        out = jnp.concatenate([jnp.moveaxis(toks, 0, 1), last[:, None]],
                              axis=1)
        return jnp.concatenate([input_ids, out], axis=1)

    return jax.jit(run)


def _generic_generate(model, input_ids, gc: GenerationConfig, key):
    """Fallback for models without a cache path: recompute the full prefix
    each step (O(n) forwards). Correct for any causal LM returning logits."""
    ids = input_ids
    done = jnp.zeros((ids.shape[0],), bool)
    for _ in range(gc.max_new_tokens):
        with _span("generation.decode_step"):
            out = model(Tensor(ids))
        logits = (out[0] if isinstance(out, tuple) else out)._data
        key, sub = jax.random.split(key)
        nxt = _sample(logits[:, -1].astype(jnp.float32), sub, gc,
                      jnp.float32(gc.temperature), jnp.float32(gc.top_p))
        if gc.eos_token_id is not None:
            nxt = jnp.where(done, gc.eos_token_id, nxt)
            done = done | (nxt == gc.eos_token_id)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return ids


def generate(model, input_ids, max_new_tokens=32, do_sample=False,
             temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
             seed=None):
    """Generate continuations. Returns (batch, prompt+max_new_tokens) ids.

    LlamaForCausalLM runs the compiled KV-cache path (one jit: prefill +
    lax.scan decode); other causal LMs use the recompute fallback.
    """
    gc = GenerationConfig(max_new_tokens, do_sample, temperature, top_k,
                          top_p, eos_token_id)
    ids = input_ids._data if isinstance(input_ids, Tensor) \
        else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    if max_new_tokens <= 0:
        # both paths must agree: zero new tokens returns the prompt as-is
        # (the compiled llama path would otherwise still emit first_tok)
        return Tensor(ids)
    if do_sample:
        key = (jax.random.key(seed) if seed is not None
               else _random.next_key())
    else:  # greedy uses no randomness — don't advance the global stream
        key = jax.random.key(0)
    from .models.llama import LlamaForCausalLM
    # one trace id per call; children (build / prefill_decode) inherit it
    # through the span stack, same correlation scheme as serving Requests
    tid = _new_trace_id("gen-") if _tracer().enabled else None
    if isinstance(model, LlamaForCausalLM):
        _metric("generation_requests_total", path="llama_compiled").inc()
        with _span("generation.generate", path="llama_compiled",
                   batch=int(ids.shape[0]), prompt=int(ids.shape[1]),
                   new_tokens=int(max_new_tokens), trace_id=tid):
            from .parallel.functional import split_stacked_layer_params
            # CURRENT weights fetched per call and passed as jit arguments —
            # the compiled program is keyed only on config/shapes, never
            # holds weight copies, and stays correct across optimizer steps
            state = {k: v._data for k, v in model.state_dict().items()}
            stacked, other = split_stacked_layer_params(state)
            tied = "lm_head.weight" not in other
            c = model.config
            # structural knobs only: temperature/top_p are traced arguments,
            # so per-request knob changes never recompile
            cache_key = ((c.hidden_size, c.num_hidden_layers,
                          c.num_attention_heads, c.num_key_value_heads,
                          c.vocab_size, c.rms_norm_eps, c.rope_theta, tied),
                         max_new_tokens, do_sample, int(top_k),
                         top_p < 1.0, eos_token_id)
            cached = _GEN_CACHE.get(cache_key)
            if cached is None:
                # prefill + decode fuse into ONE compiled program here, so
                # the trace can only split build (trace/compile) from run;
                # the serving engine's two-program path is where separate
                # prefill/decode spans nest (serving.prefill/.decode_step)
                with _span("generation.build"):
                    cached = _build_llama_generate(c, tied, gc)
                    _GEN_CACHE[cache_key] = cached
            head_w = other.get("lm_head.weight")
            if head_w is None:  # jit needs concrete leaf; tied path ignores
                head_w = jnp.zeros((0,), jnp.float32)
            with _span("generation.prefill_decode"):
                out = cached(stacked, other["llama.embed_tokens.weight"],
                             other["llama.norm.weight"], head_w, ids, key,
                             jnp.float32(temperature), jnp.float32(top_p))
                if _tracer().enabled:
                    # sync only when tracing, so the span covers device
                    # time; the disabled path keeps async dispatch
                    out.block_until_ready()
            return Tensor(out)
    _metric("generation_requests_total", path="generic_recompute").inc()
    with _span("generation.generate", path="generic_recompute",
               batch=int(ids.shape[0]), prompt=int(ids.shape[1]),
               new_tokens=int(max_new_tokens), trace_id=tid):
        return Tensor(_generic_generate(model, ids, gc, key))


_GEN_CACHE: dict = {}


class WeightOnlyGenerator:
    """Weight-only int8 serving wrapper for LlamaForCausalLM.

    Snapshots the model's weights ONCE, stores every stacked per-layer
    matmul weight (and the untied lm head) as int8 with per-output-channel
    scales, and dequantizes INSIDE the compiled generate program — weights
    sit in HBM at 1 byte/param. This is the serving analog of the
    reference's weight-only GEMM path (python/paddle/nn/quant/
    weight_quantize + weight_only_linear over the fused decode kernels in
    paddle/phi/kernels/fusion/gpu/). Embeddings and norm vectors stay in
    the compute dtype (a gather and tiny vectors gain nothing from int8).

    The dequantized bf16 copy exists transiently per call (XLA materializes
    it ahead of the prefill/decode scans); steady-state HBM holds only the
    int8 weights, which is what lets a bigger model fit a serving chip.
    """

    def __init__(self, model, max_new_tokens=32, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
                 algo="weight_only_int8", share_weights_from=None):
        from .models.llama import LlamaForCausalLM
        from .parallel.functional import split_stacked_layer_params
        if not isinstance(model, LlamaForCausalLM):
            raise TypeError(
                "WeightOnlyGenerator supports LlamaForCausalLM; for other "
                "models use generate() with externally quantized weights")
        if algo != "weight_only_int8":
            raise NotImplementedError(
                f"algo={algo!r}: only weight_only_int8 is supported "
                "(int4 packing has no TPU-native gain over int8 here)")
        self._gc = GenerationConfig(max_new_tokens, do_sample, temperature,
                                    top_k, top_p, eos_token_id)
        if share_weights_from is not None:
            # reuse another generator's quantized tensors (e.g. serving
            # the same snapshot at several generation lengths) — only the
            # compiled program differs
            src = share_weights_from
            self._q, self._s, self._fp = src._q, src._s, src._fp
            self._embed, self._norm = src._embed, src._norm
            self._qh, self._sh = src._qh, src._sh
            self._tied = src._tied
        else:
            state = {k: v._data for k, v in model.state_dict().items()}
            stacked, other = split_stacked_layer_params(state)
            self._tied = "lm_head.weight" not in other

            def quant(v):
                # per-output-channel absmax: contraction axis is -2 (h @ w
                # with w[..., in, out]), so scales live per out column
                scale = jnp.maximum(
                    jnp.max(jnp.abs(v.astype(jnp.float32)), axis=-2,
                            keepdims=True) / 127.0, 1e-8)
                q = jnp.clip(jnp.round(v.astype(jnp.float32) / scale),
                             -127, 127).astype(jnp.int8)
                return q, scale

            self._q, self._s, self._fp = {}, {}, {}
            for k, v in stacked.items():
                if v.ndim >= 3:          # (L, in, out) matmul weights
                    self._q[k], self._s[k] = quant(v)
                else:                    # (L, H) norm vectors
                    self._fp[k] = v
            self._embed = other["llama.embed_tokens.weight"]
            self._norm = other["llama.norm.weight"]
            if self._tied:
                self._qh = jnp.zeros((0, 0), jnp.int8)
                self._sh = jnp.zeros((0, 0), jnp.float32)
            else:
                self._qh, self._sh = quant(other["lm_head.weight"])
        run = _build_llama_generate(model.config, self._tied, self._gc)
        cdt = self._embed.dtype
        tied = self._tied

        def qrun(q, s, fp, embed_w, norm_w, qh, sh, ids, key, temp, top_p):
            # dequantize in fp32, THEN cast: rounding the fp32 scale to the
            # bf16 compute dtype first would double the per-weight error
            layers = dict(fp)
            for k in q:
                layers[k] = (q[k].astype(jnp.float32) * s[k]).astype(cdt)
            head = (jnp.zeros((0,), jnp.float32) if tied
                    else (qh.astype(jnp.float32) * sh).astype(cdt))
            return run(layers, embed_w, norm_w, head, ids, key, temp, top_p)

        self._qrun = jax.jit(qrun)

    def quantized_bytes(self):
        """HBM held by the quantized weights (int8 + scales + fp leftovers)."""
        total = sum(a.nbytes for a in self._q.values())
        total += sum(a.nbytes for a in self._s.values())
        total += sum(a.nbytes for a in self._fp.values())
        return total + self._embed.nbytes + self._norm.nbytes \
            + self._qh.nbytes + self._sh.nbytes

    def generate(self, input_ids, seed=None):
        ids = input_ids._data if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        ids = ids.astype(jnp.int32)
        if self._gc.max_new_tokens <= 0:
            return Tensor(ids)
        if self._gc.do_sample:
            key = (jax.random.key(seed) if seed is not None
                   else _random.next_key())
        else:
            key = jax.random.key(0)
        return Tensor(self._qrun(
            self._q, self._s, self._fp, self._embed, self._norm,
            self._qh, self._sh, ids, key,
            jnp.float32(self._gc.temperature),
            jnp.float32(self._gc.top_p)))
