"""Mesh federation for the observability plane: per-replica samplers
merged under a bounded ``replica`` label, plus one mesh-level sampler
over the process-wide registry.

In-process mesh replicas share ONE metrics registry, so mesh-wide
aggregates (finished totals, TTFT/TPOT histograms, slo gauges) already
federate for free — the mesh-level MetricsSampler scrapes them and
evaluates RECORDING_RULES with an ``alive_filter`` over the pool's
lease membership, so a killed replica's frozen ``mesh_replica_headroom``
gauge cannot poison headroom_min/headroom_sum. What does NOT federate
for free is per-replica state: each Replica therefore carries its own
MetricsSampler whose scrape source is a pseudo metrics-snapshot built
from ``Replica.snapshot()`` (replica_* gauges and counters below), so
counter→rate conversion and retention apply uniformly.

Cardinality discipline mirrors the serving engine's tenant-overflow
cap: the first ``max_replicas`` distinct replica names get their own
``replica`` label value, later joins collapse to ``"overflow"`` — a
join storm cannot blow up the merged series set.

Freeze semantics: ``tick()`` samples ONLY alive replicas. A killed
replica keeps its sampler and every point it ever recorded (the
postmortem evidence), but its series stop advancing — frozen, listed
under ``frozen`` in merged_doc()/summary() — and the alive_filter
drops it from mesh aggregates. A rejoin resumes sampling on the same
series.

Failure semantics: a replica sampler that fails degrades ITSELF (plane
off for that replica, counted); a collector-level failure degrades the
whole collector. Either way ``MeshCollector.degraded`` goes True and
serving is untouched — the same obs.sample contract as timeseries.py.
"""

from __future__ import annotations

from .catalog import metric as _metric
from .timeseries import DEFAULT_RETENTION, MetricsSampler

__all__ = ["MeshCollector", "replica_scrape", "MAX_REPLICA_LABELS"]

MAX_REPLICA_LABELS = 16


def _gauge(name, value):
    return {"name": name, "type": "gauge", "help": "", "labelnames": (),
            "samples": [{"labels": {}, "value": float(value or 0.0)}]}


def _counter(name, value):
    return {"name": name, "type": "counter", "help": "", "labelnames": (),
            "samples": [{"labels": {}, "value": float(value or 0.0)}]}


def replica_scrape(rep):
    """Zero-arg scrape callable for one Replica: its snapshot() as a
    metrics-snapshot-format doc (gauges for point-in-time state,
    counters for cumulative accounting so the sampler rates them)."""
    def scrape():
        s = rep.snapshot()
        return {"format": 1, "metrics": [
            _gauge("replica_load", s.get("load")),
            _gauge("replica_predicted_service_seconds",
                   s.get("predicted_service_s")),
            _gauge("replica_alive", 1.0 if s.get("alive") else 0.0),
            _counter("replica_routed_total", s.get("routed")),
            _counter("replica_finished_total", s.get("finished")),
            _counter("replica_tokens_total", s.get("tokens")),
            _counter("replica_steps_total", s.get("steps")),
            _counter("replica_step_seconds_total", s.get("step_seconds")),
        ]}
    return scrape


class MeshCollector:
    """Router-side federation point: one sampler per alive replica plus
    a mesh-level registry sampler, ticked together from the router pump
    (deterministic — ``now`` defaults to an internal tick counter)."""

    def __init__(self, pool, retention=DEFAULT_RETENTION,
                 max_replicas=MAX_REPLICA_LABELS):
        self.pool = pool
        self.retention = max(1, int(retention))
        self.max_replicas = max(1, int(max_replicas))
        self.enabled = True
        self._degraded = False
        self._labels = {}   # replica name -> bounded label value
        self._reps = {}     # replica name -> Replica (ever attached)
        self.ticks = 0
        self._auto_tick = 0.0
        self.mesh_sampler = MetricsSampler(
            retention=self.retention,
            alive_filter=lambda: {r.name for r in pool.alive()})

    # --- label bounding (tenant-overflow discipline) ------------------

    def label_for(self, name):
        lab = self._labels.get(name)
        if lab is None:
            lab = (name if len(self._labels) < self.max_replicas
                   else "overflow")
            self._labels[name] = lab
        return lab

    # --- the pump tick -----------------------------------------------

    def tick(self, now=None):
        """Sample every ALIVE replica plus the mesh-level registry.
        Returns True when the tick landed; any failure degrades the
        collector (plane off, serving untouched) and returns False."""
        if not self.enabled:
            return False
        try:
            if now is None:
                now = self._auto_tick
            now = float(now)
            self._auto_tick = now + 1.0
            for rep in self.pool.alive():
                smp = getattr(rep, "sampler", None)
                if smp is None:
                    smp = MetricsSampler(scrape=replica_scrape(rep),
                                         retention=self.retention)
                    rep.sampler = smp
                self._reps[rep.name] = rep
                self.label_for(rep.name)
                smp.sample(now)
            self.mesh_sampler.sample(now)
            self.ticks += 1
            return True
        except Exception:
            self.enabled = False
            self._degraded = True
            try:
                _metric("obs_plane_degradations_total",
                        what="collector").inc()
            except Exception:
                pass
            return False

    # --- state --------------------------------------------------------

    @property
    def degraded(self):
        if self._degraded or self.mesh_sampler.degraded:
            return True
        return any(getattr(rep, "sampler", None) is not None
                   and rep.sampler.degraded
                   for rep in self._reps.values())

    def frozen(self):
        """Replica names with recorded series but a dead lease — their
        series no longer advance and mesh aggregates exclude them."""
        alive = {r.name for r in self.pool.alive()}
        return sorted(set(self._reps) - alive)

    def latest(self, rule):
        """Latest mesh-level value of a recording rule (or None)."""
        return self.mesh_sampler.rule_latest(rule)

    def replica_stats(self):
        """name -> Replica.snapshot() for every ever-attached replica
        (the advisor's drain-prediction input)."""
        return {name: rep.snapshot()
                for name, rep in sorted(self._reps.items())}

    def merged_doc(self):
        """Federated TSDB snapshot (format 1): every per-replica series
        tagged with its bounded ``replica`` label, mesh-level series
        untagged, plus membership (alive / frozen)."""
        series = []
        for name, rep in sorted(self._reps.items()):
            smp = getattr(rep, "sampler", None)
            if smp is None:
                continue
            lab = self.label_for(name)
            for row in smp.snapshot_doc()["series"]:
                row["labels"] = dict(row["labels"], replica=lab)
                series.append(row)
        series.extend(self.mesh_sampler.snapshot_doc()["series"])
        return {"format": 1, "replicas": sorted(self._reps),
                "alive": sorted(r.name for r in self.pool.alive()),
                "frozen": self.frozen(), "ticks": self.ticks,
                "series": series}

    def summary(self):
        """Plane-state summary for reports: the mesh sampler's rule
        summary plus membership and federation counters."""
        out = self.mesh_sampler.summary()
        out["replicas"] = sorted(self._reps)
        out["frozen"] = self.frozen()
        out["ticks"] = self.ticks
        out["degraded"] = self.degraded
        out["enabled"] = self.enabled
        out["replica_series"] = sum(
            len(rep.sampler.series) for rep in self._reps.values()
            if getattr(rep, "sampler", None) is not None)
        return out
