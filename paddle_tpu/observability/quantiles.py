"""Histogram quantile estimation by bucket interpolation.

THE quantile implementation: `tools/metrics_dump.py`'s p50/p95/p99
columns, the SLO engine (slo.py), and `tools/slo_report.py` all call
into this file — one estimator, so an SLO verdict and an operator's
dump can never disagree about what "p95 TTFT" means.

Semantics follow Prometheus `histogram_quantile`: within the bucket
containing the target rank, the value is linearly interpolated between
the previous bound and the bucket's upper bound (the lowest bucket
interpolates from 0). A rank landing in the +Inf overflow bucket clamps
to the largest finite bound — the estimator never invents a value above
what the buckets can support.

Deliberately STANDALONE like metrics.py: stdlib only, no
package-relative imports, loadable via
importlib.util.spec_from_file_location on machines without jax.
"""

from __future__ import annotations

__all__ = ["quantile_from_cumulative", "quantiles_from_cumulative",
           "quantiles_from_sample", "DEFAULT_QS"]

# the columns metrics_dump prints and the SLO defaults reference
DEFAULT_QS = (0.5, 0.95, 0.99)


def _norm_buckets(buckets):
    """-> ([(finite_le, cum), ...] sorted, total_count). Accepts the
    [(le, cum), ...] shape of Histogram.cumulative_buckets() and the
    [[le, cum], ...] shape of a metrics snapshot sample, with le either
    a float or the string '+Inf'."""
    finite = []
    total = 0
    for le, cum in buckets:
        cum = int(cum)
        if isinstance(le, str) and le.strip() in ("+Inf", "inf", "Inf"):
            total = max(total, cum)
            continue
        le = float(le)
        if le == float("inf"):
            total = max(total, cum)
            continue
        finite.append((le, cum))
        total = max(total, cum)
    finite.sort()
    return finite, total


def quantile_from_cumulative(buckets, q):
    """Estimate the q-quantile (q in [0, 1]) from cumulative histogram
    buckets ([(le, cumulative_count), ...], '+Inf' last as emitted by
    Histogram.cumulative_buckets() / snapshot samples). Returns None for
    an empty histogram."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    finite, total = _norm_buckets(buckets)
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_cum = 0.0, 0
    for le, cum in finite:
        if cum >= rank:
            in_bucket = cum - prev_cum
            if in_bucket <= 0:
                return le
            frac = (rank - prev_cum) / in_bucket
            return prev_le + (le - prev_le) * min(max(frac, 0.0), 1.0)
        prev_le, prev_cum = le, cum
    # rank fell in the +Inf overflow: clamp to the largest finite bound
    # (None when the histogram has no finite bounds at all)
    return finite[-1][0] if finite else None


def quantiles_from_cumulative(buckets, qs=DEFAULT_QS):
    """{q: estimate_or_None} for several quantiles at once."""
    return {q: quantile_from_cumulative(buckets, q) for q in qs}


def quantiles_from_sample(sample, qs=DEFAULT_QS):
    """Same, from one histogram sample dict of a metrics snapshot
    ({'buckets': [[le, cum], ...], 'count': n, ...})."""
    return quantiles_from_cumulative(sample.get("buckets") or (), qs)
