"""Span tracer: nested host-side timing with Chrome-trace JSON export.

reference capability: python/paddle/profiler/utils.py RecordEvent +
event_tracing.h host ranges — generalized into a parent/child span tree
on monotonic clocks that the profiler's `_ChromeTracingHandler` exports
(chrome://tracing / Perfetto load the emitted file directly).

STANDALONE like metrics.py: stdlib only, loadable outside the package.

Two entry points:
  - `span(name, **args)` — the gated context manager the hot paths use;
    when tracing is disabled it returns a shared no-op (no allocation).
  - `Tracer.begin/end` — ungated; profiler.RecordEvent uses these so its
    spans are ALWAYS recorded (pre-existing profiler contract).
"""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["Span", "Tracer", "get_tracer", "span", "trace",
           "enable", "disable", "enabled", "new_trace_id",
           "LANE_TID_BASE"]

# bound the in-memory buffer: long-running serving processes must not
# grow without limit. The ring IS the bound — when it wraps, the oldest
# spans are dropped and counted (Tracer.dropped_spans; the package wires
# tracer_dropped_spans_total onto on_drop) so a leak-free engine that
# under-exports is visible, not silent. Raise via Tracer(maxlen=...).
DEFAULT_MAXLEN = 20000

# request-scoped spans exported per serving lane get synthetic Chrome
# tids in this range so the trace viewer groups them by lane, not by the
# host thread that happened to book-keep them
LANE_TID_BASE = 1 << 20

_NEXT_TRACE = [0]
_TRACE_LOCK = threading.Lock()


def new_trace_id(prefix="t"):
    """Process-unique trace id: <prefix><pid-hex>-<counter-hex>. Cheap
    (no entropy syscall) and stable enough to join spans, exemplars, and
    flight-recorder events for one request."""
    with _TRACE_LOCK:
        _NEXT_TRACE[0] += 1
        n = _NEXT_TRACE[0]
    return f"{prefix}{os.getpid():x}-{n:06x}"


class Span:
    __slots__ = ("name", "t0_ns", "dur_ns", "tid", "seq", "parent", "args",
                 "trace_id", "links")

    def __init__(self, name, t0_ns, tid, seq, parent=None, args=None,
                 trace_id=None, links=None):
        self.name = name
        self.t0_ns = t0_ns
        self.dur_ns = None          # set by end()
        self.tid = tid
        self.seq = seq
        self.parent = parent        # parent span NAME ('' at top level)
        self.args = args
        self.trace_id = trace_id    # request-scoped correlation id
        self.links = links          # trace/span ids this span links to


class _Noop:
    """Shared zero-allocation context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


class Tracer:
    def __init__(self, enabled=False, maxlen=DEFAULT_MAXLEN):
        self._state_enabled = enabled
        self._maxlen = maxlen
        self._lock = threading.Lock()
        self._finished: list[Span] = []
        self._seq = 0
        self._local = threading.local()   # per-thread open-span stack
        self.dropped_spans = 0            # ring-wrap casualties (total)
        self.on_drop = None               # callable(n) — package wires the
                                          # tracer_dropped_spans_total counter
        self._tid_names: dict[int, str] = {}   # synthetic tid -> group label

    # -- enable switch -------------------------------------------------------
    @property
    def enabled(self):
        return self._state_enabled

    def enable(self):
        self._state_enabled = True

    def disable(self):
        self._state_enabled = False

    # -- recording (ungated core) -------------------------------------------
    def _stack(self):
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def begin(self, name, args=None, trace_id=None) -> Span:
        """Open a span unconditionally (profiler path). Pair with end()."""
        stack = self._stack()
        with self._lock:
            seq = self._seq
            self._seq += 1
        sp = Span(name, time.perf_counter_ns(), threading.get_ident(), seq,
                  parent=stack[-1].name if stack else "", args=args,
                  trace_id=trace_id)
        if trace_id is None and stack and stack[-1].trace_id is not None:
            sp.trace_id = stack[-1].trace_id    # inherit down the tree
        stack.append(sp)
        return sp

    def end(self, sp: Span):
        sp.dur_ns = time.perf_counter_ns() - sp.t0_ns
        stack = self._stack()
        # tolerate mispaired ends (a crashed child left on the stack)
        while stack and stack[-1] is not sp:
            stack.pop()
        if stack:
            stack.pop()
        with self._lock:
            self._finished.append(sp)
            self._trim_locked()

    def _trim_locked(self):
        over = len(self._finished) - self._maxlen
        if over > 0:
            del self._finished[:over]
            self.dropped_spans += over
            cb = self.on_drop
            if cb is not None:
                try:
                    cb(over)
                except Exception:   # noqa: BLE001 — tracing never raises
                    pass

    def add_span(self, name, t0_ns, dur_ns, trace_id=None, args=None,
                 tid=None, tid_name=None, links=None, parent=""):
        """Record an already-measured span retroactively — no interaction
        with the thread-local nesting stack. This is how the serving
        engine books request phases (queued, prefill chunks, decode-tile
        shares) whose lifetime spans many engine-thread stack frames."""
        with self._lock:
            seq = self._seq
            self._seq += 1
            sp = Span(name, int(t0_ns),
                      threading.get_ident() if tid is None else int(tid),
                      seq, parent=parent, args=args, trace_id=trace_id,
                      links=list(links) if links else None)
            sp.dur_ns = max(int(dur_ns), 0)
            if tid is not None and tid_name is not None:
                self._tid_names.setdefault(int(tid), str(tid_name))
            self._finished.append(sp)
            self._trim_locked()
        return sp

    # -- gated context manager / decorator ----------------------------------
    def span(self, name, **args):
        if not self._state_enabled:
            return _NOOP
        trace_id = args.pop("trace_id", None)
        return _SpanCtx(self, name, args or None, trace_id)

    def trace(self, name=None):
        """Decorator form: @tracer.trace("my.phase")."""
        def wrap(fn):
            label = name or fn.__qualname__

            def inner(*a, **kw):
                if not self._state_enabled:
                    return fn(*a, **kw)
                sp = self.begin(label)
                try:
                    return fn(*a, **kw)
                finally:
                    self.end(sp)
            inner.__name__ = fn.__name__
            inner.__qualname__ = fn.__qualname__
            inner.__doc__ = fn.__doc__
            return inner
        return wrap

    # -- inspection / export -------------------------------------------------
    def marker(self) -> int:
        """Sequence watermark; pass to spans_since()/export for 'only what
        happened after this point' (profiler start() snapshots one)."""
        with self._lock:
            return self._seq

    def spans_since(self, marker=0):
        with self._lock:
            return [s for s in self._finished if s.seq >= marker]

    def clear(self):
        with self._lock:
            self._finished.clear()

    def durations_by_name(self, marker=0):
        """{name: [seconds, ...]} — backs profiler.Profiler.summary()."""
        out: dict[str, list] = {}
        for s in self.spans_since(marker):
            if s.dur_ns is not None:
                out.setdefault(s.name, []).append(s.dur_ns / 1e9)
        return out

    def chrome_trace_events(self, marker=0):
        """Chrome-trace 'X' (complete) events; nesting renders from
        timestamp containment per tid, parent also kept in args."""
        pid = os.getpid()
        events = []
        seen_tids = set()
        for s in self.spans_since(marker):
            if s.dur_ns is None:
                continue
            args = dict(s.args) if s.args else {}
            if s.parent:
                args["parent"] = s.parent
            if s.trace_id is not None:
                args["trace_id"] = s.trace_id
            if s.links:
                args["links"] = list(s.links)
            seen_tids.add(s.tid)
            events.append({"name": s.name, "ph": "X", "pid": pid,
                           "tid": s.tid, "ts": s.t0_ns / 1e3,
                           "dur": s.dur_ns / 1e3, "args": args})
        # name synthetic lane tids so the viewer groups request spans by
        # lane; only emitted when such spans exist (plain engine traces
        # keep their exact event set)
        with self._lock:
            named = [(t, n) for t, n in sorted(self._tid_names.items())
                     if t in seen_tids]
        for tid, label in named:
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": label}})
        return events

    def export_chrome_trace(self, path, marker=0):
        doc = {"traceEvents": self.chrome_trace_events(marker),
               "displayTimeUnit": "ms"}
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


class _SpanCtx:
    __slots__ = ("_tracer", "_name", "_args", "_span", "_trace_id")

    def __init__(self, tracer, name, args, trace_id=None):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._trace_id = trace_id

    def __enter__(self):
        self._span = self._tracer.begin(self._name, self._args,
                                        trace_id=self._trace_id)
        return self._span

    def __exit__(self, *exc):
        self._tracer.end(self._span)
        return False


# --------------------------------------------------------------------------
# default (process-wide) tracer
# --------------------------------------------------------------------------

_default_tracer: Tracer | None = None
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = Tracer(
                    enabled=os.environ.get("FLAGS_observability", "")
                    .lower() in ("1", "true", "yes", "on"))
    return _default_tracer


def span(name, **args):
    """Module-level `with span("serving.step"):` over the default tracer."""
    return get_tracer().span(name, **args)


def trace(name=None):
    return get_tracer().trace(name)


def enable():
    get_tracer().enable()


def disable():
    get_tracer().disable()


def enabled() -> bool:
    return get_tracer().enabled
