"""Span tracer: nested host-side timing with Chrome-trace JSON export.

reference capability: python/paddle/profiler/utils.py RecordEvent +
event_tracing.h host ranges — generalized into a parent/child span tree
on monotonic clocks that the profiler's `_ChromeTracingHandler` exports
(chrome://tracing / Perfetto load the emitted file directly).

STANDALONE like metrics.py: stdlib only, loadable outside the package.

Two entry points:
  - `span(name, **args)` — the gated context manager the hot paths use;
    when tracing is disabled it returns a shared no-op (no allocation).
  - `Tracer.begin/end` — ungated; profiler.RecordEvent uses these so its
    spans are ALWAYS recorded (pre-existing profiler contract).
"""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["Span", "Tracer", "get_tracer", "span", "trace",
           "enable", "disable", "enabled"]

# bound the in-memory buffer: long-running serving processes must not
# grow without limit; export regularly or raise via Tracer(maxlen=...)
DEFAULT_MAXLEN = 20000


class Span:
    __slots__ = ("name", "t0_ns", "dur_ns", "tid", "seq", "parent", "args")

    def __init__(self, name, t0_ns, tid, seq, parent=None, args=None):
        self.name = name
        self.t0_ns = t0_ns
        self.dur_ns = None          # set by end()
        self.tid = tid
        self.seq = seq
        self.parent = parent        # parent span NAME ('' at top level)
        self.args = args


class _Noop:
    """Shared zero-allocation context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


class Tracer:
    def __init__(self, enabled=False, maxlen=DEFAULT_MAXLEN):
        self._state_enabled = enabled
        self._maxlen = maxlen
        self._lock = threading.Lock()
        self._finished: list[Span] = []
        self._seq = 0
        self._local = threading.local()   # per-thread open-span stack

    # -- enable switch -------------------------------------------------------
    @property
    def enabled(self):
        return self._state_enabled

    def enable(self):
        self._state_enabled = True

    def disable(self):
        self._state_enabled = False

    # -- recording (ungated core) -------------------------------------------
    def _stack(self):
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def begin(self, name, args=None) -> Span:
        """Open a span unconditionally (profiler path). Pair with end()."""
        stack = self._stack()
        with self._lock:
            seq = self._seq
            self._seq += 1
        sp = Span(name, time.perf_counter_ns(), threading.get_ident(), seq,
                  parent=stack[-1].name if stack else "", args=args)
        stack.append(sp)
        return sp

    def end(self, sp: Span):
        sp.dur_ns = time.perf_counter_ns() - sp.t0_ns
        stack = self._stack()
        # tolerate mispaired ends (a crashed child left on the stack)
        while stack and stack[-1] is not sp:
            stack.pop()
        if stack:
            stack.pop()
        with self._lock:
            self._finished.append(sp)
            if len(self._finished) > self._maxlen:
                del self._finished[:len(self._finished) - self._maxlen]

    # -- gated context manager / decorator ----------------------------------
    def span(self, name, **args):
        if not self._state_enabled:
            return _NOOP
        return _SpanCtx(self, name, args or None)

    def trace(self, name=None):
        """Decorator form: @tracer.trace("my.phase")."""
        def wrap(fn):
            label = name or fn.__qualname__

            def inner(*a, **kw):
                if not self._state_enabled:
                    return fn(*a, **kw)
                sp = self.begin(label)
                try:
                    return fn(*a, **kw)
                finally:
                    self.end(sp)
            inner.__name__ = fn.__name__
            inner.__qualname__ = fn.__qualname__
            inner.__doc__ = fn.__doc__
            return inner
        return wrap

    # -- inspection / export -------------------------------------------------
    def marker(self) -> int:
        """Sequence watermark; pass to spans_since()/export for 'only what
        happened after this point' (profiler start() snapshots one)."""
        with self._lock:
            return self._seq

    def spans_since(self, marker=0):
        with self._lock:
            return [s for s in self._finished if s.seq >= marker]

    def clear(self):
        with self._lock:
            self._finished.clear()

    def durations_by_name(self, marker=0):
        """{name: [seconds, ...]} — backs profiler.Profiler.summary()."""
        out: dict[str, list] = {}
        for s in self.spans_since(marker):
            if s.dur_ns is not None:
                out.setdefault(s.name, []).append(s.dur_ns / 1e9)
        return out

    def chrome_trace_events(self, marker=0):
        """Chrome-trace 'X' (complete) events; nesting renders from
        timestamp containment per tid, parent also kept in args."""
        pid = os.getpid()
        events = []
        for s in self.spans_since(marker):
            if s.dur_ns is None:
                continue
            args = dict(s.args) if s.args else {}
            if s.parent:
                args["parent"] = s.parent
            events.append({"name": s.name, "ph": "X", "pid": pid,
                           "tid": s.tid, "ts": s.t0_ns / 1e3,
                           "dur": s.dur_ns / 1e3, "args": args})
        return events

    def export_chrome_trace(self, path, marker=0):
        doc = {"traceEvents": self.chrome_trace_events(marker),
               "displayTimeUnit": "ms"}
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


class _SpanCtx:
    __slots__ = ("_tracer", "_name", "_args", "_span")

    def __init__(self, tracer, name, args):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._span = self._tracer.begin(self._name, self._args)
        return self._span

    def __exit__(self, *exc):
        self._tracer.end(self._span)
        return False


# --------------------------------------------------------------------------
# default (process-wide) tracer
# --------------------------------------------------------------------------

_default_tracer: Tracer | None = None
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = Tracer(
                    enabled=os.environ.get("FLAGS_observability", "")
                    .lower() in ("1", "true", "yes", "on"))
    return _default_tracer


def span(name, **args):
    """Module-level `with span("serving.step"):` over the default tracer."""
    return get_tracer().span(name, **args)


def trace(name=None):
    return get_tracer().trace(name)


def enable():
    get_tracer().enable()


def disable():
    get_tracer().disable()


def enabled() -> bool:
    return get_tracer().enabled
