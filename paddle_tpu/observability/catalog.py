"""Canonical metric-name catalog.

EVERY metric the framework emits is declared here — instrumentation
sites fetch handles via `metric(name, **labels)`, which refuses names
not in the catalog, and OBSERVABILITY.md's table is generated from /
checked against this dict (tests/test_observability.py pins both
directions, so docs and code cannot drift).

Entry: name -> (type, help, labelnames, buckets_or_None).
"""

from __future__ import annotations

from . import metrics as _metrics

__all__ = ["CATALOG", "metric", "register_all"]

# latency bucket families (seconds)
_TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                 10.0, 30.0)
_TPOT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 1.0)
_STEP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5, 5.0, 15.0, 60.0)

CATALOG = {
    # -- serving (inference/serving.py ContinuousBatchingEngine) ------------
    "serving_ttft_seconds": (
        "histogram", "time from add_request to the first sampled token",
        (), _TTFT_BUCKETS),
    "serving_tpot_seconds": (
        "histogram", "per-token decode latency: one compiled decode step "
        "(all active lanes advance one token)", (), _TPOT_BUCKETS),
    "serving_prefill_seconds": (
        "histogram", "one prefill program call (bucketed prompt)",
        (), _STEP_BUCKETS),
    "serving_queue_depth": (
        "gauge", "requests waiting for admission", (), None),
    "serving_batch_occupancy": (
        "gauge", "active lanes / max_batch (0..1)", (), None),
    "serving_kv_free_blocks": (
        "gauge", "free blocks in the paged KV pool", (), None),
    "serving_admitted_total": (
        "counter", "requests admitted to a decode lane", (), None),
    "serving_retired_total": (
        "counter", "requests finished and released", (), None),
    "serving_rejected_total": (
        "counter", "requests rejected as unservable",
        ("reason",), None),
    "serving_deferred_total": (
        "counter", "admissions deferred (request stays queued)",
        ("reason",), None),
    "serving_preempted_total": (
        "counter", "mid-flight preemptions (0 by design: whole-sequence "
        "admission; counted so a future preempting scheduler is visible)",
        (), None),
    "serving_tokens_total": (
        "counter", "tokens emitted across all requests", (), None),

    # -- generation (generation.py) -----------------------------------------
    "generation_requests_total": (
        "counter", "generate() calls by execution path",
        ("path",), None),

    # -- attention router (ops/pallas/attention_router.py) ------------------
    "attention_router_decisions_total": (
        "counter", "fresh (non-cached) routing decisions by source",
        ("source",), None),

    # -- training telemetry (observability.stepwatch.StepWatch) -------------
    "train_step_seconds": (
        "histogram", "train-step wall time", (), _STEP_BUCKETS),
    "train_tokens_total": (
        "counter", "training tokens consumed", (), None),
    "train_loss": ("gauge", "latest training loss", (), None),
    "train_grad_norm": ("gauge", "latest global grad norm", (), None),
    "train_tokens_per_s": ("gauge", "online training throughput", (), None),
    "train_mfu": (
        "gauge", "online model-FLOPs utilization (needs flops_per_token "
        "and peak_flops)", (), None),

    # -- elastic / distributed recovery --------------------------------------
    "elastic_membership_changes_total": (
        "counter", "ElasticManager.watch observed the alive set change",
        (), None),
    "elastic_restarts_total": (
        "counter", "ElasticManager returned RESTART (regroup requested)",
        (), None),
    "elastic_pod_restarts_total": (
        "counter", "launcher restarted the local pod after worker failure",
        (), None),
    "checkpoint_saves_total": (
        "counter", "distributed checkpoint save_state_dict calls", (), None),
    "checkpoint_loads_total": (
        "counter", "distributed checkpoint load_state_dict calls (resume "
        "path after elastic restart)", (), None),

    # -- bench orchestration (bench.py parent; stage = probe/configN/...) ----
    "bench_attempts_total": (
        "counter", "bench worker subprocess attempts by stage and outcome",
        ("stage", "outcome"), None),
    "bench_probe_timeouts_total": (
        "counter", "TPU liveness probes that hit their wall-clock timeout "
        "(tunnel dark/wedged)", (), None),
}


def register_all(registry=None):
    """Define every catalog metric on `registry` (default: the process
    registry). Idempotent; conflicting duplicates raise in the registry."""
    reg = registry or _metrics.get_registry()
    for name, (mtype, help_, labelnames, buckets) in CATALOG.items():
        if mtype == "histogram":
            reg.histogram(name, help_, labelnames,
                          buckets or _metrics.DEFAULT_BUCKETS)
        elif mtype == "gauge":
            reg.gauge(name, help_, labelnames)
        else:
            reg.counter(name, help_, labelnames)
    return reg


def metric(name, **labels):
    """Instrumentation-site handle: get-or-register `name` from the
    catalog on the default registry; unknown names raise (add them to
    the CATALOG + OBSERVABILITY.md first — that is the point)."""
    try:
        mtype, help_, labelnames, buckets = CATALOG[name]
    except KeyError:
        raise KeyError(f"{name!r} is not in the observability catalog "
                       "(paddle_tpu/observability/catalog.py)") from None
    reg = _metrics.get_registry()
    if mtype == "histogram":
        fam = reg.histogram(name, help_, labelnames,
                            buckets or _metrics.DEFAULT_BUCKETS)
    elif mtype == "gauge":
        fam = reg.gauge(name, help_, labelnames)
    else:
        fam = reg.counter(name, help_, labelnames)
    return fam.labels(**labels) if labels else fam
