"""Canonical metric-name catalog.

EVERY metric the framework emits is declared here — instrumentation
sites fetch handles via `metric(name, **labels)`, which refuses names
not in the catalog, and OBSERVABILITY.md's table is generated from /
checked against this dict (tests/test_observability.py pins both
directions, so docs and code cannot drift).

Entry: name -> (type, help, labelnames, buckets_or_None).
"""

from __future__ import annotations

from . import metrics as _metrics

__all__ = ["CATALOG", "metric", "register_all"]

# latency bucket families (seconds)
_TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                 10.0, 30.0)
_TPOT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 1.0)
_STEP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5, 5.0, 15.0, 60.0)
# ratio buckets (0..1) — acceptance rates and other fractions
_RATE_BUCKETS = (0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)
# phase segments span sub-ms marks to multi-second cold compiles
_PHASE_BUCKETS = (0.00005, 0.0002, 0.001, 0.005, 0.025, 0.1, 0.5, 2.5,
                  10.0, 60.0)
# measured/predicted cost ratios, log-ish around the ideal 1.0
_COST_RATIO_BUCKETS = (0.1, 0.2, 0.5, 0.8, 1.0, 1.25, 2.0, 5.0, 10.0)
# wire bytes of one paged-KV handoff: tiny CPU-proxy prompts land in the
# low KB buckets, production-shape blocks in the MB range
_HANDOFF_BUCKETS = (1e3, 4e3, 16e3, 64e3, 256e3, 1e6, 4e6, 16e6, 64e6)

CATALOG = {
    # -- serving (inference/serving.py ContinuousBatchingEngine) ------------
    "serving_ttft_seconds": (
        "histogram", "time from add_request to the first sampled token",
        (), _TTFT_BUCKETS),
    "serving_tpot_seconds": (
        "histogram", "per-token decode latency: dispatch->readback wall "
        "time of one fused K-step decode tile over K (all active lanes "
        "advance K tokens per dispatch)", (), _TPOT_BUCKETS),
    "serving_prefill_seconds": (
        "histogram", "one prefill chunk program call (chunked prompt)",
        (), _STEP_BUCKETS),
    "serving_queue_depth": (
        "gauge", "requests waiting for admission", (), None),
    "serving_batch_occupancy": (
        "gauge", "active lanes / max_batch (0..1)", (), None),
    "serving_kv_free_blocks": (
        "gauge", "free blocks in the paged KV pool", (), None),
    "serving_admitted_total": (
        "counter", "requests admitted to a decode lane", (), None),
    "serving_retired_total": (
        "counter", "requests finished and released", (), None),
    "serving_rejected_total": (
        "counter", "requests rejected as unservable",
        ("reason",), None),
    "serving_deferred_total": (
        "counter", "admissions deferred (request stays queued)",
        ("reason",), None),
    "serving_preempted_total": (
        "counter", "mid-flight decode-lane preemptions by the SLO "
        "scheduler (unlabelled total; serving_preemptions_total is the "
        "by-class sibling)", (), None),
    "serving_preemptions_total": (
        "counter", "decode-lane preemptions by priority class of the "
        "preempted request — paged-KV blocks stay resident and the "
        "stream resumes byte-identically", ("class",), None),
    "serving_brownout_level": (
        "gauge", "current brownout-ladder level index (0 = normal; the "
        "closed, ordered registry is inference/scheduler.py "
        "BROWNOUT_LEVELS, documented in RESILIENCE.md)", (), None),
    "serving_brownout_transitions_total": (
        "counter", "brownout-ladder level transitions by direction (up "
        "= escalate under SLO pressure, down = recover with "
        "hysteresis)", ("direction",), None),
    "serving_quota_deferrals_total": (
        "counter", "admissions deferred because the tenant sits at its "
        "lane quota (the DRR pick skips it; the request stays queued)",
        ("tenant",), None),
    "serving_tokens_total": (
        "counter", "tokens emitted across all requests", (), None),
    "serving_finished_total": (
        "counter", "requests finished by finish_reason "
        "(eos/length/timeout/shed/rejected) — degraded completions are "
        "distinguishable", ("reason",), None),
    "serving_timeouts_total": (
        "counter", "per-request deadlines expired, by where the request "
        "was (queue/decode/preempted, plus the router-side 'handoff' "
        "sweep for streams parked between replicas — invisible to both "
        "engines' own sweeps)", ("where",), None),
    "serving_shed_total": (
        "counter", "decode-OOM lane sheds (request requeued for a fresh "
        "prefill, or finished 'shed' past max_sheds)", (), None),
    "serving_backpressure_total": (
        "counter", "add_request refusals at max_queue (BackpressureError)",
        (), None),
    "serving_route_probe_failures_total": (
        "counter", "audit attention-route probes that failed at engine "
        "construction (logged, engine continues)", (), None),
    "serving_pool_exhausted_total": (
        "counter", "paged-KV-pool reservations refused "
        "(KVPoolExhaustedError raised; caller defers or sheds)", (), None),
    "serving_lane_state_uploads_total": (
        "counter", "device lane-state refreshes from the host mirrors "
        "(only on lane membership change: admit/retire/shed; steady-state "
        "decode uploads nothing)", (), None),
    "serving_decode_dispatches_total": (
        "counter", "fused K-step decode tiles dispatched (compare with "
        "serving_lane_state_uploads_total: uploads << dispatches)",
        (), None),
    "serving_dispatch_ahead_depth": (
        "gauge", "in-flight decode tiles at dispatch time (1 = "
        "double-buffered: host bookkeeping overlaps device compute)",
        (), None),
    "serving_hostsync_seconds": (
        "histogram", "host blocked reading back a decode token tile "
        "(device->host sync; the overlap design keeps this small)",
        (), _TPOT_BUCKETS),
    "serving_hostsync_retries_total": (
        "counter", "transient token-tile readback failures (tile kept "
        "in flight, retried next step)", (), None),
    "serving_prefill_chunks_total": (
        "counter", "prefill chunk program calls (long prompts interleave "
        "with decode instead of head-of-line blocking)", (), None),
    "serving_draft_tokens_total": (
        "counter", "draft tokens proposed by the speculative decoder "
        "(draft_depth per lane per scan step)", (), None),
    "serving_accepted_tokens_total": (
        "counter", "draft tokens accepted by the batched verify forward "
        "(accepted/drafted is the acceptance rate; the committed stream "
        "also gets one correction token per step on top)", (), None),
    "serving_spec_acceptance_rate": (
        "histogram", "per-drained-tile draft acceptance rate (0..1); the "
        "exemplar carries the trace id of the WORST-accepting request in "
        "the tile, so a low bucket links to the request to turn "
        "speculation off for", (), _RATE_BUCKETS),
    "serving_kv_dequant_seconds": (
        "histogram", "wall time of a whole-pool KV dequantization (the "
        "serve.kv_dequant drop-to-bf16 degradation path)", (),
        _STEP_BUCKETS),
    "serving_tokens_per_dispatch": (
        "gauge", "tokens credited from the last drained decode tile (one "
        "dispatch): K per lane without speculation, up to K*(draft_depth"
        "+1) per lane with it", (), None),
    "serving_runtime_degradations_total": (
        "counter", "permanent runtime degradations taken by the engine "
        "(speculation_off: draft/verify fault -> non-speculative decode; "
        "kv_bf16: dequant fault -> pool dequantized to the native dtype; "
        "sched_fifo: scheduler decision fault -> plain FIFO admission; "
        "prefix_miss: prefix-index fault -> that one lookup/insert "
        "treated as a cache miss, full prefill, stream unchanged)",
        ("what",), None),
    "serving_prefix_hits_total": (
        "counter", "admissions whose prompt resolved >= 1 leading block "
        "from the cross-request prefix cache (prefill runs only on the "
        "unmatched tail)", (), None),
    "serving_prefix_misses_total": (
        "counter", "admissions (prefix cache enabled) whose prompt "
        "resolved nothing from the index — including lookups degraded "
        "by a serve.prefix_match fault", (), None),
    "serving_prefix_tokens_saved_total": (
        "counter", "prompt tokens NOT prefilled because their blocks "
        "were resolved from the prefix cache (hit_rate * mean matched "
        "length in one number; the bench prefill-skip evidence)",
        (), None),
    "serving_prefix_shared_blocks": (
        "gauge", "paged-KV blocks currently pinned by the prefix index "
        "(each holds one block-aligned prompt chunk; refcount-shared "
        "with any resident requests that adopted it)", (), None),
    "serving_prefix_evictions_total": (
        "counter", "prefix-index entries evicted (LRU leaf under pool "
        "pressure or the prefix_cache_blocks cap, plus whole-index "
        "clears on a block-format degradation)", (), None),
    "serving_prefix_cow_forks_total": (
        "counter", "copy-on-write block forks: a block-aligned "
        "full-prefix match re-prefills its final prompt position into a "
        "private copy of the last shared block (the only write that can "
        "target a shared block)", (), None),
    "serving_adapter_loads_total": (
        "counter", "adapter hot-loads into a device pool slot, by "
        "adapter name (bounded by the store's closed registry)",
        ("adapter",), None),
    "serving_adapter_evictions_total": (
        "counter", "idle adapter slots LRU-evicted to make room for a "
        "cold acquire, by the evicted adapter's name", ("adapter",),
        None),
    "serving_adapter_resident": (
        "gauge", "named adapters currently resident in the device "
        "weight pool (slot 0, the all-zeros base, is not counted)",
        (), None),
    "serving_adapter_load_failures_total": (
        "counter", "adapter acquisitions that failed typed (unknown "
        "name, all slots pinned, or an injected serve.adapter_load / "
        "serve.adapter_gather fault); each one is a "
        "finish_reason=rejected admission, never a wrong-weights "
        "stream", (), None),
    "serving_adapter_upload_seconds": (
        "histogram", "host dispatch wall of one adapter's A/B pool "
        "upload (the copy itself is async and overlaps in-flight "
        "decode tiles)", (), _STEP_BUCKETS),
    "serving_adapter_quota_deferrals_total": (
        "counter", "admission picks skipped because the candidate's "
        "adapter was at its concurrent-lane quota (adapter DRR riding "
        "the tenant scheduler)", ("adapter",), None),
    "serving_adapter_ttft_seconds": (
        "histogram", "per-adapter time to first token (label 'base' = "
        "slot-0 requests; cardinality bounded by the store's closed "
        "registry)", ("adapter",), _TTFT_BUCKETS),
    "serving_adapter_tpot_seconds": (
        "histogram", "per-adapter per-token decode latency (same tile "
        "wall as serving_tpot_seconds, attributed to each adapter the "
        "tile advanced)", ("adapter",), _TPOT_BUCKETS),
    "serving_phase_seconds": (
        "histogram", "one phase-attributed segment of engine step wall "
        "time, by profiler phase (closed registry in "
        "paddle_tpu/profiler/phases.py; segments partition the step)",
        ("phase",), _PHASE_BUCKETS),
    "serving_phase_coverage_ratio": (
        "gauge", "cumulative phase-attributed time / measured engine "
        "step wall time (0..1); the harness gates on >= 0.95", (), None),
    "serving_tenant_ttft_seconds": (
        "histogram", "per-tenant time to first token (bounded-cardinality "
        "sibling of serving_ttft_seconds; unattributed tenant is '-', "
        "overflow past the cap collapses to 'overflow')",
        ("tenant",), _TTFT_BUCKETS),
    "serving_tenant_tpot_seconds": (
        "histogram", "per-tenant per-token decode latency "
        "(bounded-cardinality sibling of serving_tpot_seconds)",
        ("tenant",), _TPOT_BUCKETS),
    "serving_tenant_finished_total": (
        "counter", "requests finished, by tenant and finish_reason "
        "(bounded-cardinality sibling of serving_finished_total)",
        ("tenant", "reason"), None),
    "serving_overload": (
        "gauge", "1.0 while the engine is saturated (predicted service "
        "demand exceeds capacity: slo_headroom <= 0), else 0.0 — the "
        "shed-before-collapse early-warning the loadgen harness asserts "
        "on", (), None),

    # -- generation (generation.py) -----------------------------------------
    "generation_requests_total": (
        "counter", "generate() calls by execution path",
        ("path",), None),

    # -- attention router (ops/pallas/attention_router.py) ------------------
    "attention_router_decisions_total": (
        "counter", "fresh (non-cached) routing decisions by source",
        ("source",), None),

    # -- training telemetry (observability.stepwatch.StepWatch) -------------
    "train_step_seconds": (
        "histogram", "train-step wall time", (), _STEP_BUCKETS),
    "train_tokens_total": (
        "counter", "training tokens consumed", (), None),
    "train_loss": ("gauge", "latest training loss", (), None),
    "train_grad_norm": ("gauge", "latest global grad norm", (), None),
    "train_tokens_per_s": ("gauge", "online training throughput", (), None),
    "train_mfu": (
        "gauge", "online model-FLOPs utilization (needs flops_per_token "
        "and peak_flops)", (), None),
    "train_nonfinite_skips_total": (
        "counter", "batches skipped by the TrainSupervisor for a "
        "non-finite loss", (), None),
    "train_preemptions_total": (
        "counter", "SIGTERM preemptions handled gracefully (final "
        "checkpoint + clean exit)", (), None),

    # -- elastic / distributed recovery --------------------------------------
    "elastic_membership_changes_total": (
        "counter", "ElasticManager.watch observed the alive set change",
        (), None),
    "elastic_restarts_total": (
        "counter", "ElasticManager returned RESTART (regroup requested)",
        (), None),
    "elastic_pod_restarts_total": (
        "counter", "launcher restarted the local pod after worker failure",
        (), None),
    "checkpoint_saves_total": (
        "counter", "distributed checkpoint save_state_dict calls", (), None),
    "checkpoint_loads_total": (
        "counter", "distributed checkpoint load_state_dict calls (resume "
        "path after elastic restart)", (), None),
    "elastic_heartbeat_recoveries_total": (
        "counter", "heartbeat store writes that succeeded after >=1 retry "
        "(transient store fault survived)", (), None),
    "elastic_watch_recoveries_total": (
        "counter", "membership-watch store reads that succeeded after "
        ">=1 retry", (), None),
    "elastic_beat_failures_total": (
        "counter", "threaded-heartbeat iterations that failed past the "
        "retry budget (the daemon beat loop keeps going — the lease may "
        "still survive within its ttl; never raised into serving)",
        (), None),

    # -- resilience (paddle_tpu/resilience/: faults, retry) ------------------
    "fault_injected_total": (
        "counter", "faults fired by the injection harness, by site "
        "(FLAGS_fault_injection / resilience.faults)", ("site",), None),
    "resilience_retries_total": (
        "counter", "transient-failure retries by RetryPolicy, by op",
        ("op",), None),
    "resilience_retry_giveups_total": (
        "counter", "retry budgets exhausted (last error re-raised), by op",
        ("op",), None),
    "resilience_circuit_open_total": (
        "counter", "circuit breakers tripping open, by op", ("op",), None),

    # -- PIR compiler layer (paddle_tpu/pir/: capture, passes, cache) --------
    "pir_captures_total": (
        "counter", "programs captured (jaxpr -> pir.Program lowerings)",
        (), None),
    "pir_pass_seconds": (
        "histogram", "wall time of one PIR pass run, by pass",
        ("pass",), _STEP_BUCKETS),
    "pir_pass_edits_total": (
        "counter", "IR edits applied (ops removed/folded/merged/"
        "rewritten), by pass", ("pass",), None),
    "pir_fallback_total": (
        "counter", "pipeline degradations to plain jax.jit, by stage "
        "(capture/verify/fuse/passes/evaluator)", ("stage",), None),
    "pir_verify_seconds": (
        "histogram", "wall time of one structural verifier run over a "
        "captured program (pir/verifier.py; after capture and after "
        "passes per FLAGS_pir_verify)", (), _STEP_BUCKETS),
    "pir_verify_failures_total": (
        "counter", "programs rejected by the IR verifier, by rule "
        "(def-before-use/single-def/arity/dangling-value/dead-code/"
        "effect-order/type-mismatch/donation-alias/sharding-conflict/"
        "verifier-error); each rejection degrades that compile to "
        "plain jax.jit", ("rule",), None),
    "jit_retrace_total": (
        "counter", "compiled-program (re)constructions: StaticFunction "
        "traces for a new input signature, plus serving decode/prefill "
        "program builds (shape or variant churn is visible here; the "
        "adapter hot-swap contract pins its delta to 0 across churn)",
        (), None),
    "compile_cache_hit_total": (
        "counter", "persistent compile-cache hits (verified artifact "
        "deserialized; XLA compile skipped)", (), None),
    "compile_cache_miss_total": (
        "counter", "persistent compile-cache misses (fresh compile)",
        (), None),
    "compile_cache_write_total": (
        "counter", "compile-cache artifacts written", (), None),
    "compile_cache_corrupt_total": (
        "counter", "artifacts that failed sha256/format verification "
        "(typed CompileCacheCorruptionError; recovered by recompile)",
        (), None),
    "compile_cache_evict_total": (
        "counter", "artifacts LRU-evicted past the size cap", (), None),
    "compile_cache_bytes": (
        "gauge", "compile-cache directory size after the last write",
        (), None),
    "pir_cost_ratio": (
        "gauge", "measured / roofline-predicted wall time of the last "
        "dispatch of the named compiled program (pir/analysis.py "
        "CostModel; 1.0 = the static price was exact)", ("program",), None),
    "pir_cost_model_error": (
        "histogram", "measured/predicted cost ratio per dispatch, all "
        "programs pooled; the exemplar carries the PROGRAM NAME, so the "
        "top bucket's exemplar names the worst-predicted program",
        (), _COST_RATIO_BUCKETS),
    "pir_sharding_annotations_total": (
        "counter", "Value.sharding annotations committed by the "
        "sharding-propagation pass (pir/shard_prop.py), by program — "
        "fixpoint output, not user input: input annotations spread "
        "through the whole IR land here", ("program",), None),
    "pir_shard_search_seconds": (
        "histogram", "wall time of one cost-driven sharding search "
        "(pir/shard_search.py; bounded candidate enumeration priced "
        "by the CostModel roofline+ICI estimate)", (), _STEP_BUCKETS),
    "pir_exposed_comm_seconds": (
        "gauge", "CostModel exposed-communication seconds of the named "
        "program after the collective-overlap pass committed a "
        "schedule (pir/overlap.py; comm the overlap credit did not "
        "hide)", ("program",), None),
    "pir_fusion_groups_total": (
        "counter", "pt.fused_region groups committed by the auto-fusion "
        "pass (pir/fuse.py), by program — each group passed the strict "
        "predicted bytes-traffic-decrease criterion", ("program",), None),
    "pir_fusion_bytes_saved": (
        "counter", "predicted HBM bytes-traffic saved by committed "
        "fusion groups (CostModel.group_bytes_saved: unfused member "
        "traffic minus fused boundary traffic), by program",
        ("program",), None),
    "pir_fusion_groups_by_kind_total": (
        "counter", "committed fusion groups by provenance kind — chain "
        "(v1 single-output), multi_output (promoted sibling-shared "
        "results), epilogue (dot_general / nested-region anchor "
        "absorbed) — by program (pir/fuse.py GROUP_KINDS)",
        ("program", "kind"), None),
    "pir_fuse_seconds": (
        "histogram", "wall time of one auto-fusion pass run (planning "
        "walk + group commits; pir/fuse.py)", (), _STEP_BUCKETS),

    # -- telemetry loop (tracing ring, flight recorder, SLO engine) ----------
    "tracer_dropped_spans_total": (
        "counter", "finished spans evicted when the bounded tracer ring "
        "wrapped (raise Tracer(maxlen=...) or export more often)", (), None),
    "flight_recorder_dumps_total": (
        "counter", "flight-recorder postmortem dumps written, by reason "
        "(unhandled_error/preempt/drill:<site>/manual)", ("reason",), None),
    "slo_compliance": (
        "gauge", "1.0 when the named SLO currently meets its objective, "
        "else 0.0 (slo.SLOEngine.evaluate)", ("slo",), None),
    "slo_burn_rate": (
        "gauge", "error-budget burn rate of the named SLO (1.0 = burning "
        "exactly the budget; >1 exhausts it early); for quantile SLOs, "
        "observed/target ratio", ("slo",), None),
    "slo_headroom": (
        "gauge", "remaining serving capacity as a fraction of capacity: "
        "1 - arrival_rate * predicted_seconds_per_request (cost-model "
        "calibrated); <= 0 means offered load exceeds what the engine "
        "can serve and goodput will collapse unless load sheds", (), None),

    # -- load generator (inference/loadgen.py + tools/loadgen.py) ------------
    "loadgen_arrivals_total": (
        "counter", "requests injected by the open-loop traffic harness, "
        "by scenario", ("scenario",), None),
    "loadgen_ticks_skipped_total": (
        "counter", "harness clock ticks skipped after a "
        "serve.loadgen_tick fault (arrivals from the skipped tick are "
        "re-issued on the next one — open-loop schedule preserved)",
        (), None),

    # -- serving mesh (inference/mesh/: router, disaggregated handoff) -------
    "mesh_routed_total": (
        "counter", "requests the mesh router committed to the named "
        "replica (after the mesh.route fault site and the replica's "
        "CircuitBreaker both let the pick through)", ("replica",), None),
    "mesh_handoffs_total": (
        "counter", "serialized paged-KV prefill->decode handoffs, by "
        "outcome (ok / retried / re_prefill — re_prefill means the "
        "wire transfer was abandoned and the decode side re-ran "
        "prefill from the prompt)", ("outcome",), None),
    "mesh_failovers_total": (
        "counter", "requests re-routed off a replica, by reason "
        "(replica_down / circuit_open / route_fault / admit_failed)",
        ("reason",), None),
    "mesh_handoff_bytes": (
        "histogram", "serialized wire size of one paged-KV handoff "
        "(payload + scales + prompt metadata; quantized block formats "
        "shrink this ~2-4x at identical streams)", (), _HANDOFF_BUCKETS),
    "mesh_replica_headroom": (
        "gauge", "per-replica slo_headroom snapshot the router balanced "
        "on at its last pick (1 - offered_load * predicted service "
        "seconds; <=0 = saturated, routed around when possible)",
        ("replica",), None),
    "mesh_transport_frames_total": (
        "counter", "framed request/response round trips between the "
        "router and process-backed workers, by frame kind (transport.py; "
        "loopback and socket transports both count)", ("kind",), None),
    "mesh_controller_actions_total": (
        "counter", "autoscale controller actions taken on advisor "
        "verdicts (scale_up / drain_begin / scale_down / drain_forced / "
        "latch_off — latch_off means a controller failure flipped it "
        "back to advisory-only)", ("action",), None),
    "mesh_rpc_timeouts_total": (
        "counter", "transport op waits that expired past their budget, "
        "by op (frame kind): client-side result()/drain expiry AND "
        "worker-side rejection of already-expired work both count — "
        "every one raises typed TransportTimeout, the gray-failure "
        "signal (reply still owed, replica NOT latched lost)",
        ("op",), None),
    "mesh_replica_suspicion": (
        "gauge", "per-replica phi-accrual suspicion score from the "
        "health detector (inter-progress latency while busy; 0 = "
        "progressing or idle; crosses the SLOW threshold before the "
        "DEAD one by construction)", ("replica",), None),
    "mesh_slow_demotions_total": (
        "counter", "health-detector SLOW verdicts per replica: the "
        "replica is demoted out of _ranked (no new placements, existing "
        "streams keep running) until it progresses again — the gray "
        "middle ground between healthy and the replica_down path",
        ("replica",), None),
    "mesh_hedges_total": (
        "counter", "hedged recoveries, by outcome: launched (a parked "
        "handoff or in-flight prefill outlived the latency budget and a "
        "speculative duplicate started on the next-best replica) / win "
        "(the hedge committed first) / cancelled (the losing duplicate "
        "was withdrawn from its worker) — first finish wins through the "
        "at-most-once commit map, streams byte-identical",
        ("outcome",), None),

    # -- observability plane (timeseries.py sampler + mesh federation) -------
    "obs_samples_total": (
        "counter", "successful MetricsSampler scrape ticks (timeseries.py; "
        "one per landed tick across every sampler in the process)", (), None),
    "obs_plane_degradations_total": (
        "counter", "observability-plane failures that flipped a sampler or "
        "collector to degraded (plane off, serving untouched), by failure "
        "class (obs.sample fault site)", ("what",), None),

    # -- bench orchestration (bench.py parent; stage = probe/configN/...) ----
    "bench_attempts_total": (
        "counter", "bench worker subprocess attempts by stage and outcome",
        ("stage", "outcome"), None),
    "bench_probe_timeouts_total": (
        "counter", "TPU liveness probes that hit their wall-clock timeout "
        "(tunnel dark/wedged)", (), None),
}


def register_all(registry=None):
    """Define every catalog metric on `registry` (default: the process
    registry). Idempotent; conflicting duplicates raise in the registry."""
    reg = registry or _metrics.get_registry()
    for name, (mtype, help_, labelnames, buckets) in CATALOG.items():
        if mtype == "histogram":
            reg.histogram(name, help_, labelnames,
                          buckets or _metrics.DEFAULT_BUCKETS)
        elif mtype == "gauge":
            reg.gauge(name, help_, labelnames)
        else:
            reg.counter(name, help_, labelnames)
    return reg


def metric(name, **labels):
    """Instrumentation-site handle: get-or-register `name` from the
    catalog on the default registry; unknown names raise (add them to
    the CATALOG + OBSERVABILITY.md first — that is the point)."""
    try:
        mtype, help_, labelnames, buckets = CATALOG[name]
    except KeyError:
        raise KeyError(f"{name!r} is not in the observability catalog "
                       "(paddle_tpu/observability/catalog.py)") from None
    reg = _metrics.get_registry()
    if mtype == "histogram":
        fam = reg.histogram(name, help_, labelnames,
                            buckets or _metrics.DEFAULT_BUCKETS)
    elif mtype == "gauge":
        fam = reg.gauge(name, help_, labelnames)
    else:
        fam = reg.counter(name, help_, labelnames)
    return fam.labels(**labels) if labels else fam
