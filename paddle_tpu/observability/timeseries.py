"""Embedded time-series store: deterministic scrapes of the metrics
registry into bounded ring-buffer series, plus the closed registry of
recording rules evaluated into derived series every tick.

The design contract (round 17):

- **Deterministic tick.** ``MetricsSampler.sample(now=None)`` never
  reads a wall clock. ``now`` is whatever monotone clock the caller
  owns: the serving engine passes nothing (an internal tick counter),
  mesh replicas pass their step counter, the load generator passes
  schedule time. Tests hand-drive the clock and get bit-identical
  series.
- **Bounded.** Every series is a ring buffer of at most ``retention``
  points, and raw-series cardinality is capped at ``max_series`` —
  past the cap new series are dropped and counted, the tenant-overflow
  discipline applied to series keys. ``rule/*`` series are exempt:
  RECORDING_RULES is a closed registry, bounded by construction.
- **Counter→rate.** Counters are stored as per-window rates
  (delta / dt); histograms keep the previous cumulative buckets so the
  quantile rules are *windowed* (this window's observations only) and
  computed by THE shared estimator (quantiles.quantile_from_cumulative)
  — a recording rule and an operator's metrics_dump can never disagree
  about what "p95 TTFT" means.
- **Never raises.** Any failure inside ``sample()`` — including the
  chaos-drilled ``obs.sample`` fault site — flips the sampler to
  degraded (plane off), bumps ``obs_plane_degradations_total{what}``
  and returns False. Serving is never touched (drill-pinned
  byte-identical greedy streams).

Snapshot format (``snapshot_doc()`` / ``load_doc()``, format 1)::

    {"format": 1, "tick": <last now or None>, "retention": N,
     "series": [{"name": ..., "labels": {...}, "kind":
                 "gauge"|"rate"|"derived", "points": [[t, v], ...]}]}

The round-trip restores every point; the rate/window priming state is
deliberately NOT serialized — the first ``sample()`` after a load
re-primes counters, so one tick of rates is skipped, never wrong.
"""

from __future__ import annotations

import collections

from .catalog import metric as _metric
from .metrics import get_registry, snapshot
from .quantiles import quantile_from_cumulative

__all__ = ["RECORDING_RULES", "Series", "MetricsSampler", "load_doc",
           "DEFAULT_RETENTION", "MAX_SERIES"]

DEFAULT_RETENTION = 512
MAX_SERIES = 256

# The closed registry of recording rules: name -> meaning. Every rule
# is evaluated into a derived series named ``rule/<name>`` on each tick
# (from the second tick on — rules are windowed and need a previous
# scrape). static_check.py rule "recording-rules" pins this dict to the
# `rule/NAME` table in OBSERVABILITY.md, both directions, and
# tests/test_timeseries.py pins it to _RULE_EVALUATORS.
RECORDING_RULES = {
    "goodput_rate": "finished-good requests per second (finish_reason "
                    "eos/length) over the tick window",
    "shed_fraction": "fraction of this window's finishes that were "
                     "shed/rejected (0.0 when nothing finished)",
    "ttft_p95": "p95 time-to-first-token over the tick window "
                "(shared estimator; holds last value on empty windows)",
    "tpot_p99": "p99 per-token decode latency over the tick window "
                "(shared estimator; holds last value on empty windows)",
    "slo_burn_rate": "max error-budget burn rate across SLOs "
                     "(0.0 when no SLO has reported)",
    "headroom_min": "min per-replica headroom across ALIVE replicas "
                    "(falls back to slo_headroom; 1.0 when unknown)",
    "headroom_sum": "sum of per-replica headroom across ALIVE replicas "
                    "(falls back to slo_headroom; 0.0 when unknown)",
    "brownout_max": "max brownout-ladder level across replicas "
                    "(0.0 = every engine normal)",
}

_GOOD_REASONS = ("eos", "length")
_SHED_REASONS = ("shed", "rejected")


class Series:
    """One bounded ring-buffer series of (t, value) points."""

    __slots__ = ("name", "labels", "kind", "points")

    def __init__(self, name, labels=(), kind="gauge",
                 retention=DEFAULT_RETENTION):
        self.name = str(name)
        if isinstance(labels, dict):
            labels = labels.items()
        self.labels = tuple(sorted(labels))
        self.kind = str(kind)
        self.points = collections.deque(maxlen=max(1, int(retention)))

    def add(self, t, value):
        self.points.append((float(t), float(value)))

    def latest(self):
        return self.points[-1][1] if self.points else None

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"Series({self.name!r}, {dict(self.labels)!r}, "
                f"kind={self.kind!r}, n={len(self.points)})")


class _Window:
    """One tick's view of the scrape: per-metric gauge values, counter
    deltas, and histogram cumulative-bucket deltas — the only inputs a
    recording rule may read (keeps rules windowed by construction)."""

    __slots__ = ("dt", "gauges", "counter_deltas", "hist_deltas")

    def __init__(self, dt):
        self.dt = dt
        self.gauges = {}          # name -> [(labels_dict, value), ...]
        self.counter_deltas = {}  # name -> [(labels_dict, delta), ...]
        self.hist_deltas = {}     # name -> [[(le, delta_cum), ...], ...]


def _bucket_delta(cur, prev):
    """Windowed cumulative buckets: per-le delta of two cumulative
    snapshots (still cumulative in le, so the shared estimator applies
    directly)."""
    return [(le, max(0.0, float(c) - float(p)))
            for (le, c), (_ple, p) in zip(cur, prev)]


class MetricsSampler:
    """Scrape a metrics-snapshot-format source on a deterministic tick
    into bounded ring-buffer series and evaluate RECORDING_RULES.

    ``scrape`` is any zero-arg callable returning a metrics snapshot
    doc (metrics.snapshot() format 1); the default scrapes the
    process-wide registry. ``alive_filter`` — a callable returning the
    set of alive replica names (or a static set, or None) — restricts
    the headroom rules to live members so a dead replica's frozen
    gauges cannot poison mesh aggregates.
    """

    def __init__(self, scrape=None, retention=DEFAULT_RETENTION,
                 max_series=MAX_SERIES, alive_filter=None):
        self._scrape = scrape
        self.retention = max(1, int(retention))
        self.max_series = max(1, int(max_series))
        self.alive_filter = alive_filter
        self.series = {}           # (name, labels_tuple) -> Series
        self.enabled = True
        self.degraded = False
        self.samples = 0
        self.dropped_series = 0
        self._raw_series = 0       # non-rule series count (cap domain)
        self._auto_tick = 0.0
        self._last_now = None
        self._prev_counters = {}   # key -> last total
        self._prev_hists = {}      # key -> last cumulative buckets
        self._rule_last = {}       # hold-last state for quantile rules

    # --- the tick ----------------------------------------------------

    def sample(self, now=None):
        """One deterministic scrape tick. Returns True when a tick
        landed; False when the sampler is disabled/degraded, the clock
        did not advance, or the tick failed (which also degrades the
        plane — never the caller)."""
        if not self.enabled:
            return False
        try:
            from ..resilience.faults import fault_point
            fault_point("obs.sample")
            if now is None:
                now = self._auto_tick
            now = float(now)
            if self._last_now is not None and now <= self._last_now:
                return False
            doc = (self._scrape() if self._scrape is not None
                   else snapshot(get_registry()))
            win = self._ingest(doc, now)
            if win.dt is not None and win.dt > 0:
                self._evaluate_rules(win, now)
            self._last_now = now
            self._auto_tick = now + 1.0
            self.samples += 1
            _metric("obs_samples_total").inc()
            return True
        except Exception as e:  # plane off, serving untouched
            self._degrade(e)
            return False

    def _degrade(self, exc):
        self.enabled = False
        self.degraded = True
        try:
            _metric("obs_plane_degradations_total",
                    what=type(exc).__name__).inc()
        except Exception:
            pass

    # --- ingestion ---------------------------------------------------

    def _ingest(self, doc, now):
        dt = None if self._last_now is None else now - self._last_now
        win = _Window(dt)
        for m in doc.get("metrics", ()):
            name, mtype = m["name"], m["type"]
            for s in m.get("samples", ()):
                labels = tuple(sorted((s.get("labels") or {}).items()))
                key = (name, labels)
                if mtype == "counter":
                    cur = float(s["value"])
                    prev = self._prev_counters.get(key)
                    self._prev_counters[key] = cur
                    if not dt:
                        continue
                    # a child born mid-window deltas from 0, not skipped
                    delta = max(0.0, cur - (prev or 0.0))
                    win.counter_deltas.setdefault(name, []).append(
                        (dict(labels), delta))
                    self._record(key, "rate", now, delta / dt)
                elif mtype == "histogram":
                    cum = [(b[0], float(b[1]))
                           for b in (s.get("buckets") or ())]
                    prev = self._prev_hists.get(key)
                    self._prev_hists[key] = cum
                    if not dt:
                        continue
                    if prev is None:   # child born mid-window
                        prev = [(le, 0.0) for le, _c in cum]
                    win.hist_deltas.setdefault(name, []).append(
                        _bucket_delta(cum, prev))
                else:  # gauge (anything point-in-time)
                    value = float(s.get("value", 0.0))
                    win.gauges.setdefault(name, []).append(
                        (dict(labels), value))
                    self._record(key, "gauge", now, value)
        return win

    def _record(self, key, kind, t, value):
        s = self.series.get(key)
        if s is None:
            if self._raw_series >= self.max_series:
                self.dropped_series += 1
                return
            s = self.series[key] = Series(key[0], key[1], kind,
                                          self.retention)
            self._raw_series += 1
        s.add(t, value)

    # --- recording rules ---------------------------------------------

    def _evaluate_rules(self, win, now):
        for name, fn in _RULE_EVALUATORS.items():
            key = ("rule/" + name, ())
            s = self.series.get(key)
            if s is None:
                s = self.series[key] = Series(key[0], (), "derived",
                                              self.retention)
            s.add(now, fn(win, self))

    def _alive(self):
        f = self.alive_filter
        if f is None:
            return None
        return set(f() if callable(f) else f)

    def _headroom_values(self, win):
        alive = self._alive()
        out = []
        for labels, v in win.gauges.get("mesh_replica_headroom", ()):
            rep = labels.get("replica")
            if alive is not None and rep is not None and rep not in alive:
                continue  # dead replica: series frozen, aggregate clean
            out.append(v)
        if not out:
            out = [v for _l, v in win.gauges.get("slo_headroom", ())]
        return out

    def _windowed_quantile(self, win, name, q, rule):
        per_series = win.hist_deltas.get(name)
        if per_series:
            merged, order = {}, []
            for buckets in per_series:
                for le, d in buckets:
                    if le not in merged:
                        merged[le] = 0.0
                        order.append(le)
                    merged[le] += d
            v = quantile_from_cumulative([(le, merged[le]) for le in order],
                                         q)
            if v is not None:
                self._rule_last[rule] = float(v)
                return float(v)
        return self._rule_last.get(rule, 0.0)

    # --- reads -------------------------------------------------------

    def latest(self, name, **labels):
        s = self.series.get((name, tuple(sorted(labels.items()))))
        return s.latest() if s is not None else None

    def rule_latest(self, rule):
        return self.latest("rule/" + rule)

    def summary(self):
        """Machine-readable plane state: per-rule latest value + point
        count, series/sample totals, degradation flags."""
        rules = {}
        for name in RECORDING_RULES:
            s = self.series.get(("rule/" + name, ()))
            rules[name] = {"latest": s.latest() if s is not None else None,
                           "points": len(s.points) if s is not None else 0}
        return {"format": 1, "rules": rules, "series": len(self.series),
                "samples": self.samples,
                "dropped_series": self.dropped_series,
                "enabled": self.enabled, "degraded": self.degraded}

    def snapshot_doc(self):
        """JSON-serializable TSDB snapshot (format 1; see module doc)."""
        series = []
        for (name, labels), s in sorted(self.series.items()):
            series.append({"name": name, "labels": dict(labels),
                           "kind": s.kind,
                           "points": [[t, v] for t, v in s.points]})
        return {"format": 1, "tick": self._last_now,
                "retention": self.retention, "series": series}


def load_doc(doc):
    """Rebuild a MetricsSampler from snapshot_doc() output — the
    round-trip tools/dashboard.py renders from. Rate/window priming
    state is not serialized: the next sample() re-primes counters."""
    if not isinstance(doc, dict) or doc.get("format") != 1:
        fmt = doc.get("format") if isinstance(doc, dict) else type(doc)
        raise ValueError(f"not a timeseries snapshot (format {fmt!r})")
    out = MetricsSampler(retention=doc.get("retention", DEFAULT_RETENTION))
    out._last_now = doc.get("tick")
    if out._last_now is not None:
        out._auto_tick = float(out._last_now) + 1.0
    for row in doc.get("series", ()):
        s = Series(row["name"], dict(row.get("labels") or {}),
                   row.get("kind", "gauge"), out.retention)
        for t, v in row.get("points", ()):
            s.add(t, v)
        out.series[(s.name, s.labels)] = s
        if not s.name.startswith("rule/"):
            out._raw_series += 1
    return out


# rule name -> evaluator(window, sampler) -> float. Total functions:
# every rule emits a point on every evaluated tick (defaults documented
# in RECORDING_RULES) so "plane on" always means populated rule series.
def _rule_goodput_rate(win, smp):
    good = sum(d for labels, d
               in win.counter_deltas.get("serving_finished_total", ())
               if labels.get("reason") in _GOOD_REASONS)
    return good / win.dt


def _rule_shed_fraction(win, smp):
    total = bad = 0.0
    for labels, d in win.counter_deltas.get("serving_finished_total", ()):
        total += d
        if labels.get("reason") in _SHED_REASONS:
            bad += d
    return bad / total if total > 0 else 0.0


def _rule_ttft_p95(win, smp):
    return smp._windowed_quantile(win, "serving_ttft_seconds", 0.95,
                                  "ttft_p95")


def _rule_tpot_p99(win, smp):
    return smp._windowed_quantile(win, "serving_tpot_seconds", 0.99,
                                  "tpot_p99")


def _rule_slo_burn_rate(win, smp):
    vals = [v for _l, v in win.gauges.get("slo_burn_rate", ())]
    return max(vals) if vals else 0.0


def _rule_headroom_min(win, smp):
    vals = smp._headroom_values(win)
    return min(vals) if vals else 1.0


def _rule_headroom_sum(win, smp):
    vals = smp._headroom_values(win)
    return sum(vals) if vals else 0.0


def _rule_brownout_max(win, smp):
    vals = [v for _l, v in win.gauges.get("serving_brownout_level", ())]
    return max(vals) if vals else 0.0


_RULE_EVALUATORS = {
    "goodput_rate": _rule_goodput_rate,
    "shed_fraction": _rule_shed_fraction,
    "ttft_p95": _rule_ttft_p95,
    "tpot_p99": _rule_tpot_p99,
    "slo_burn_rate": _rule_slo_burn_rate,
    "headroom_min": _rule_headroom_min,
    "headroom_sum": _rule_headroom_sum,
    "brownout_max": _rule_brownout_max,
}

assert set(_RULE_EVALUATORS) == set(RECORDING_RULES), \
    "RECORDING_RULES and _RULE_EVALUATORS must list the same rules"
