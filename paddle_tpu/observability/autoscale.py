"""Deterministic autoscale advisor: headroom/backlog/burn-rate series
in, a machine-readable format-1 scaling verdict out.

This is the consumer the ROADMAP autoscale item named: "autoscale
signals derived from predicted_service_seconds() exported for an
external replica controller". The advisor never acts — it emits a
verdict from ``mesh_report()`` (and ``tools/autoscale_report.py``
offline) that an external controller can apply. It is deterministic
(no wall clock, no randomness: the same signal sequence always yields
the same verdict sequence) and hysteresis-damped so advice cannot flap
on a threshold boundary.

Verdict (format 1)::

    {"format": 1,
     "action": "scale_up" | "scale_down" | "hold",   # committed
     "proposal": ...,          # this tick's raw lean, pre-hysteresis
     "reason": "...",
     "current_replicas": n, "desired_replicas": n,
     "signals": {"headroom_min": x, "headroom_sum": x,
                 "burn_rate": x, "backlog": n},
     "hysteresis": {"pending": action, "streak": n, "needed": n},
     "drain_s": {replica: predicted_seconds_to_drain, ...}}

Scaling logic: scale UP when the tightest alive replica's headroom is
below ``scale_up_headroom`` or any SLO burns its error budget faster
than ``burn_limit``; scale DOWN only when every replica has at least
``scale_down_headroom`` spare, the mesh's summed headroom could absorb
losing a whole replica (>= 1 + scale_down_headroom), and nothing is
queued. A proposal must persist ``hysteresis_ticks`` consecutive
advise() calls before it commits into ``desired_replicas``.
"""

from __future__ import annotations

__all__ = ["AutoscaleAdvisor", "VERDICT_FORMAT", "check_verdict"]

VERDICT_FORMAT = 1

_ACTIONS = ("scale_up", "hold", "scale_down")


class AutoscaleAdvisor:
    def __init__(self, scale_up_headroom=0.1, scale_down_headroom=0.5,
                 min_replicas=1, max_replicas=16, hysteresis_ticks=3,
                 burn_limit=1.0):
        self.scale_up_headroom = float(scale_up_headroom)
        self.scale_down_headroom = float(scale_down_headroom)
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.hysteresis_ticks = max(1, int(hysteresis_ticks))
        self.burn_limit = float(burn_limit)
        self._pending = "hold"
        self._streak = 0
        self._desired = None

    def _propose(self, current, headroom_min, headroom_sum, burn_rate,
                 backlog):
        if current < self.min_replicas:
            return "scale_up", (f"current {current} < min_replicas "
                                f"{self.min_replicas}")
        if headroom_min < self.scale_up_headroom and \
                current < self.max_replicas:
            return "scale_up", (f"headroom_min {headroom_min:.3f} < "
                                f"{self.scale_up_headroom:.3f}")
        if burn_rate > self.burn_limit and current < self.max_replicas:
            return "scale_up", (f"slo burn rate {burn_rate:.2f} > "
                                f"{self.burn_limit:.2f}")
        if (current > self.min_replicas and backlog == 0
                and headroom_min > self.scale_down_headroom
                and headroom_sum >= 1.0 + self.scale_down_headroom):
            return "scale_down", (f"headroom_sum {headroom_sum:.3f} "
                                  "absorbs losing one replica")
        return "hold", "within bounds"

    def advise(self, *, current_replicas, headroom_min=1.0,
               headroom_sum=None, burn_rate=0.0, backlog=0,
               replica_stats=None):
        """One deterministic advisory tick. ``replica_stats`` maps
        replica name -> Replica.snapshot()-shaped dict; per-replica
        drain predictions are load x predicted_service_s from it."""
        current = max(0, int(current_replicas))
        headroom_min = float(headroom_min)
        if headroom_sum is None:
            headroom_sum = headroom_min * max(1, current)
        headroom_sum = float(headroom_sum)
        burn_rate = float(burn_rate)
        backlog = max(0, int(backlog))

        proposal, reason = self._propose(current, headroom_min,
                                         headroom_sum, burn_rate, backlog)
        if proposal == self._pending:
            self._streak += 1
        else:
            self._pending = proposal
            self._streak = 1

        action = "hold"
        if proposal != "hold" and self._streak >= self.hysteresis_ticks:
            action = proposal
        if action == "scale_up":
            self._desired = min(self.max_replicas,
                                max(current + 1, self.min_replicas))
        elif action == "scale_down":
            self._desired = max(self.min_replicas, current - 1)
        else:
            self._desired = min(self.max_replicas,
                                max(current, self.min_replicas))

        drain = {}
        for name, st in sorted((replica_stats or {}).items()):
            load = float(st.get("load") or 0.0)
            svc = float(st.get("predicted_service_s") or 0.0)
            drain[name] = round(load * svc, 6)

        return {
            "format": VERDICT_FORMAT,
            "action": action,
            "proposal": proposal,
            "reason": reason,
            "current_replicas": current,
            "desired_replicas": int(self._desired),
            "signals": {"headroom_min": headroom_min,
                        "headroom_sum": headroom_sum,
                        "burn_rate": burn_rate,
                        "backlog": backlog},
            "hysteresis": {"pending": self._pending,
                           "streak": self._streak,
                           "needed": self.hysteresis_ticks},
            "drain_s": drain,
        }


def check_verdict(verdict):
    """-> list of problem strings (empty = verdict is well-formed and
    internally consistent). The --check gates in tools/loadgen.py and
    tools/autoscale_report.py both call this — one checker."""
    problems = []
    if not isinstance(verdict, dict):
        return [f"autoscale verdict is {type(verdict).__name__}, not dict"]
    if verdict.get("format") != VERDICT_FORMAT:
        problems.append(f"verdict format {verdict.get('format')!r} != "
                        f"{VERDICT_FORMAT}")
    action = verdict.get("action")
    if action not in _ACTIONS:
        problems.append(f"unknown action {action!r}")
    if verdict.get("proposal") not in _ACTIONS:
        problems.append(f"unknown proposal {verdict.get('proposal')!r}")
    desired = verdict.get("desired_replicas")
    current = verdict.get("current_replicas")
    if not isinstance(desired, int) or desired < 1:
        problems.append(f"desired_replicas {desired!r} must be an int >= 1")
    if not isinstance(current, int) or current < 0:
        problems.append(f"current_replicas {current!r} must be an int >= 0")
    if isinstance(desired, int) and isinstance(current, int):
        if action == "scale_up" and desired < current:
            problems.append("action scale_up but desired < current")
        if action == "scale_down" and desired > current:
            problems.append("action scale_down but desired > current")
        if abs(desired - current) > 1:
            problems.append("desired moved more than one replica in one "
                            "verdict (advice must be incremental)")
    hyst = verdict.get("hysteresis")
    if not isinstance(hyst, dict) or not all(
            k in hyst for k in ("pending", "streak", "needed")):
        problems.append("hysteresis state missing pending/streak/needed")
    elif action != "hold" and hyst["streak"] < hyst["needed"]:
        problems.append("committed action with streak below the "
                        "hysteresis threshold")
    sig = verdict.get("signals")
    if not isinstance(sig, dict) or not all(
            k in sig for k in ("headroom_min", "headroom_sum",
                               "burn_rate", "backlog")):
        problems.append("signals missing headroom_min/headroom_sum/"
                        "burn_rate/backlog")
    if not isinstance(verdict.get("drain_s"), dict):
        problems.append("drain_s per-replica predictions missing")
    return problems
