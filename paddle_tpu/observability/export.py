"""Exporter runbook helpers: one call dumps everything a process knows.

The heavy lifting lives in metrics.py (Prometheus text / JSONL snapshot)
and tracing.py (Chrome trace); this module is the convenience layer the
OBSERVABILITY.md runbook documents.
"""

from __future__ import annotations

import os

from . import metrics as _metrics
from . import recorder as _recorder
from . import tracing as _tracing

__all__ = ["prometheus_text", "snapshot", "write_snapshot_jsonl",
           "write_prometheus_text", "export_chrome_trace",
           "dump_flight_recorder", "dump_all"]


def prometheus_text(registry=None) -> str:
    return _metrics.to_prometheus_text(registry or _metrics.get_registry())


def snapshot(registry=None, meta=None) -> dict:
    return _metrics.snapshot(registry or _metrics.get_registry(), meta)


def write_snapshot_jsonl(path, registry=None, meta=None):
    return _metrics.write_snapshot_jsonl(
        path, registry or _metrics.get_registry(), meta)


def write_prometheus_text(path, registry=None):
    with open(path, "w") as f:
        f.write(prometheus_text(registry))
    return path


def export_chrome_trace(path, tracer=None, marker=0):
    return (tracer or _tracing.get_tracer()).export_chrome_trace(
        path, marker)


def dump_flight_recorder(path, rec=None, reason="manual", extra=None):
    return (rec or _recorder.get_recorder()).dump(path, reason=reason,
                                                  extra=extra)


def dump_all(dir_name, prefix="obs", registry=None, tracer=None, meta=None,
             rec=None):
    """Write <dir>/<prefix>.metrics.jsonl, .prom, .trace.json,
    .flight.json; returns the four paths. The one-call exporter for
    shutdown hooks and debugging."""
    os.makedirs(dir_name, exist_ok=True)
    p1 = write_snapshot_jsonl(
        os.path.join(dir_name, f"{prefix}.metrics.jsonl"), registry, meta)
    p2 = write_prometheus_text(
        os.path.join(dir_name, f"{prefix}.prom"), registry)
    p3 = export_chrome_trace(
        os.path.join(dir_name, f"{prefix}.trace.json"), tracer)
    p4 = dump_flight_recorder(
        os.path.join(dir_name, f"{prefix}.flight.json"), rec)
    return p1, p2, p3, p4
