"""Declarative SLOs evaluated from windowed metrics snapshots.

The sensor half of the ROADMAP's "SLO-driven scheduler": specs say what
good looks like (`TTFT p95 <= 2.5s`, `99% of requests finish well`),
the engine turns a stream of metrics snapshots into a machine-readable
verdict plus `slo_compliance` / `slo_burn_rate` catalog gauges. The
evaluation core is DETERMINISTIC — callers supply snapshot dicts and
timestamps, the engine only diffs and interpolates — so the future
scheduler PR (and today's tests) can replay exact scenarios.

Two spec kinds:

* ``quantile`` — estimate quantile ``q`` of a histogram metric over the
  window and require it <= ``objective`` (seconds). Burn rate is the
  observed/objective ratio (1.0 = exactly at target).
* ``error_budget`` — of a labeled counter's window delta, the fraction
  matching ``good`` label values must be >= ``objective``. Burn rate is
  bad_fraction / (1 - objective): >1 spends the error budget faster
  than allowed.

Quantiles come from observability/quantiles.py — the SAME estimator
tools/metrics_dump.py prints, so a verdict and an operator's dump can
never disagree.

STANDALONE like metrics.py: stdlib only; loadable by path (tools/
slo_report.py runs on machines without jax). The catalog gauges are
emitted through a guarded import that standalone loads skip.
"""

from __future__ import annotations

import json
from collections import deque

try:
    from .quantiles import quantile_from_cumulative
except ImportError:     # loaded standalone by path: sibling file, same deal
    import importlib.util as _ilu
    import os as _os
    _p = _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                       "quantiles.py")
    _s = _ilu.spec_from_file_location("_paddle_tpu_quantiles", _p)
    _m = _ilu.module_from_spec(_s)
    _s.loader.exec_module(_m)
    quantile_from_cumulative = _m.quantile_from_cumulative

__all__ = ["SLOSpec", "SLOEngine", "DEFAULT_SLOS", "parse_specs",
           "VERDICT_FORMAT"]

VERDICT_FORMAT = 1


class SLOSpec:
    """One declarative objective. `good` (error_budget only) maps a
    label name to the tuple of values that count as good outcomes.
    `labels` (optional, any kind) restricts the spec to samples whose
    labels match every given name=value pair — the per-adapter /
    per-tenant verdict scoping (round 22)."""

    __slots__ = ("name", "kind", "metric", "q", "objective", "good",
                 "labels")

    def __init__(self, name, kind, metric, objective, q=None, good=None,
                 labels=None):
        if kind not in ("quantile", "error_budget"):
            raise ValueError(f"unknown SLO kind {kind!r} "
                             "(want quantile|error_budget)")
        if kind == "quantile" and q is None:
            raise ValueError(f"SLO {name!r}: quantile kind needs q")
        if kind == "error_budget":
            if not good:
                raise ValueError(f"SLO {name!r}: error_budget needs good=")
            if not 0.0 < float(objective) < 1.0:
                raise ValueError(f"SLO {name!r}: error_budget objective "
                                 "must be in (0, 1)")
        self.name = str(name)
        self.kind = kind
        self.metric = str(metric)
        self.q = None if q is None else float(q)
        self.objective = float(objective)
        self.good = ({str(k): tuple(str(x) for x in v)
                      for k, v in good.items()} if good else None)
        self.labels = ({str(k): str(v) for k, v in labels.items()}
                       if labels else None)

    def state_key(self):
        """What one windowed observation is keyed by: two specs over
        the same metric with different label filters must not share
        state."""
        if not self.labels:
            return self.metric
        return (self.metric, tuple(sorted(self.labels.items())))

    def matches(self, sample_labels):
        if not self.labels:
            return True
        sl = sample_labels or {}
        return all(sl.get(k) == v for k, v in self.labels.items())

    @classmethod
    def from_dict(cls, d):
        return cls(d["name"], d["kind"], d["metric"], d["objective"],
                   q=d.get("q"), good=d.get("good"),
                   labels=d.get("labels"))

    def to_dict(self):
        d = {"name": self.name, "kind": self.kind, "metric": self.metric,
             "objective": self.objective}
        if self.q is not None:
            d["q"] = self.q
        if self.good is not None:
            d["good"] = {k: list(v) for k, v in self.good.items()}
        if self.labels is not None:
            d["labels"] = dict(self.labels)
        return d

    def __repr__(self):
        tail = (f"p{int(self.q * 100)}<={self.objective}"
                if self.kind == "quantile"
                else f"good>={self.objective}")
        return f"SLOSpec({self.name}: {self.metric} {tail})"


def parse_specs(doc):
    """[SLOSpec] from a JSON document (list of dicts, or a dict with a
    'slos' list) — the tools/slo_report.py --spec file format."""
    if isinstance(doc, (str, bytes)):
        doc = json.loads(doc)
    if isinstance(doc, dict):
        doc = doc.get("slos", [])
    return [SLOSpec.from_dict(d) for d in doc]


# serving defaults: TTFT p95 within 2.5s, steady decode p99 within
# 250ms/token, and 99% of finishes being genuine completions
# (eos/length — timeout/shed/rejected burn the error budget)
DEFAULT_SLOS = (
    SLOSpec("ttft_p95", "quantile", "serving_ttft_seconds",
            objective=2.5, q=0.95),
    SLOSpec("tpot_p99", "quantile", "serving_tpot_seconds",
            objective=0.25, q=0.99),
    SLOSpec("availability", "error_budget", "serving_finished_total",
            objective=0.99, good={"reason": ("eos", "length")}),
)


# -- snapshot plumbing -------------------------------------------------------

def _find_metric(snapshot_doc, name):
    for m in snapshot_doc.get("metrics", []):
        if m.get("name") == name:
            return m
    return None


def _hist_state(mdict, spec=None):
    """Merge a histogram family's samples -> {le_key: cum} (le_key is
    float or '+Inf'), summing across label children — optionally only
    the children matching the spec's label filter."""
    merged = {}
    for s in mdict.get("samples", []):
        if spec is not None and not spec.matches(s.get("labels")):
            continue
        for le, cum in s.get("buckets", []):
            key = "+Inf" if (isinstance(le, str) or le == float("inf")) \
                else float(le)
            merged[key] = merged.get(key, 0) + int(cum)
    return merged


def _counter_state(mdict, spec=None):
    """Labeled counter family -> {(sorted label items): value}."""
    out = {}
    for s in mdict.get("samples", []):
        if spec is not None and not spec.matches(s.get("labels")):
            continue
        key = tuple(sorted((s.get("labels") or {}).items()))
        out[key] = out.get(key, 0.0) + float(s.get("value", 0.0))
    return out


def _extract(snapshot_doc, specs):
    """One windowed observation: per spec metric (and label filter), the
    cumulative state needed to diff later."""
    state = {}
    for spec in specs:
        m = _find_metric(snapshot_doc, spec.metric)
        if m is None:
            continue
        state[spec.state_key()] = (
            _hist_state(m, spec) if spec.kind == "quantile"
            else _counter_state(m, spec))
    return state


def _diff_hist(new, old):
    """Cumulative-bucket delta as the [(le, cum), ...] the estimator
    eats ('+Inf' kept last)."""
    finite = sorted(k for k in new if k != "+Inf")
    out = [(le, max(0, new.get(le, 0) - (old or {}).get(le, 0)))
           for le in finite]
    out.append(("+Inf", max(0, new.get("+Inf", 0)
                            - (old or {}).get("+Inf", 0))))
    return out


def _diff_counter(new, old):
    return {k: max(0.0, v - (old or {}).get(k, 0.0)) for k, v in new.items()}


class SLOEngine:
    """Feed it snapshots over time; ask for a verdict.

        eng = SLOEngine()                      # DEFAULT_SLOS, 300s window
        eng.observe(metrics.snapshot(reg), t=now)
        verdict = eng.evaluate(t=now)          # also sets the gauges

    evaluate() diffs the newest observation against the one at (or just
    before) the window start, so the verdict reflects the last
    `window_s` seconds, not process lifetime. With a single observation
    the baseline is empty — everything ever recorded counts, which is
    exactly what a one-shot bench wants."""

    def __init__(self, specs=None, window_s=300.0):
        self.specs = list(specs if specs is not None else DEFAULT_SLOS)
        self.window_s = float(window_s)
        self._series = deque()      # (t, {metric: cumulative state})

    def observe(self, snapshot_doc, t):
        """Record one metrics snapshot taken at time `t` (caller's
        clock; only differences matter)."""
        t = float(t)
        self._series.append((t, _extract(snapshot_doc, self.specs)))
        cutoff = t - self.window_s
        # keep exactly one observation at/before the window start as the
        # diff baseline; drop anything older
        while len(self._series) >= 2 and self._series[1][0] <= cutoff:
            self._series.popleft()

    def _window(self):
        if not self._series:
            return None, None
        newest = self._series[-1][1]
        baseline = self._series[0][1] if len(self._series) >= 2 else {}
        return baseline, newest

    def evaluate(self, emit=True):
        """-> verdict dict (see VERDICT_FORMAT). Deterministic given the
        observed snapshots. When `emit`, also sets slo_compliance /
        slo_burn_rate on the process registry (skipped standalone)."""
        baseline, newest = self._window()
        results = []
        for spec in self.specs:
            r = {"name": spec.name, "kind": spec.kind,
                 "metric": spec.metric, "objective": spec.objective}
            if spec.q is not None:
                r["q"] = spec.q
            if spec.labels is not None:
                r["labels"] = dict(spec.labels)
            new = (newest or {}).get(spec.state_key())
            old = (baseline or {}).get(spec.state_key())
            if spec.kind == "quantile":
                if new is None:
                    r.update(ok=True, no_data=True, observed=None,
                             burn_rate=0.0, count=0)
                else:
                    buckets = _diff_hist(new, old)
                    count = buckets[-1][1] if buckets else 0
                    obs = quantile_from_cumulative(buckets, spec.q)
                    if obs is None:
                        r.update(ok=True, no_data=True, observed=None,
                                 burn_rate=0.0, count=0)
                    else:
                        r.update(ok=obs <= spec.objective, observed=obs,
                                 burn_rate=obs / spec.objective,
                                 count=count)
            else:   # error_budget
                if new is None:
                    r.update(ok=True, no_data=True, good=0, total=0,
                             burn_rate=0.0)
                else:
                    delta = _diff_counter(new, old)
                    total = sum(delta.values())
                    good = 0.0
                    for key, v in delta.items():
                        labels = dict(key)
                        if all(labels.get(ln) in vals
                               for ln, vals in spec.good.items()):
                            good += v
                    if total <= 0:
                        r.update(ok=True, no_data=True, good=0, total=0,
                                 burn_rate=0.0)
                    else:
                        bad_frac = (total - good) / total
                        budget = 1.0 - spec.objective
                        r.update(ok=(good / total) >= spec.objective,
                                 good=int(good), total=int(total),
                                 good_fraction=good / total,
                                 burn_rate=bad_frac / budget)
            results.append(r)
        verdict = {"format": VERDICT_FORMAT, "window_s": self.window_s,
                   "ok": all(r["ok"] for r in results), "slos": results}
        if emit:
            self._emit(results)
        return verdict

    @staticmethod
    def _emit(results):
        try:        # guarded: absent in standalone loads / metrics off
            from .catalog import metric
        except ImportError:
            return
        try:
            for r in results:
                metric("slo_compliance", slo=r["name"]).set(
                    1.0 if r["ok"] else 0.0)
                metric("slo_burn_rate", slo=r["name"]).set(
                    float(r.get("burn_rate") or 0.0))
        except Exception:   # noqa: BLE001 — verdicts never fail on gauges
            pass
