"""Thread-safe process-wide metric registry.

reference capability: the reference scatters runtime evidence across
ad-hoc artifacts (profiler host-event tables, benchmark timers in
python/paddle/profiler/timer.py, per-tool JSON logs). This module is the
single substrate: Counter / Gauge / Histogram with labels, exported as
Prometheus text or a JSONL snapshot that bench rows can embed verbatim.

Deliberately STANDALONE: stdlib only, no package-relative imports — so
`bench.py`'s orchestrating parent (which must never import jax) and
`tools/metrics_dump.py` can load this file directly via
importlib.util.spec_from_file_location.

Zero-cost when disabled: every mutation starts with one attribute check
(`self._state.enabled`) and returns before taking the lock or touching
any state — the no-op path allocates nothing per call (guarded by
tests/test_observability.py::test_disabled_noop_allocates_nothing).
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time

__all__ = ["MetricRegistry", "Counter", "Gauge", "Histogram",
           "get_registry", "to_prometheus_text", "snapshot",
           "load_snapshot", "write_snapshot_jsonl", "read_snapshot_jsonl",
           "SNAPSHOT_FORMAT", "DEFAULT_BUCKETS"]

SNAPSHOT_FORMAT = 1

# latency-oriented defaults (seconds), prometheus-client-compatible
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

# distinct label sets per metric; beyond this a labels() call raises —
# unbounded cardinality is the classic way a metrics layer eats a server
MAX_LABEL_SETS = 256


class _State:
    """Shared mutable enable flag; children cache a reference so the
    disabled fast path is a single attribute load."""

    __slots__ = ("enabled",)

    def __init__(self, enabled=False):
        self.enabled = bool(enabled)


def _env_default() -> bool:
    return os.environ.get("FLAGS_observability", "").lower() in (
        "1", "true", "yes", "on")


class _Child:
    """One (metric, label-set) time series."""

    __slots__ = ("_state", "_lock", "labels_kv")

    def __init__(self, state, labels_kv):
        self._state = state
        self._lock = threading.Lock()
        self.labels_kv = labels_kv          # tuple of (k, v) pairs, sorted


class Counter(_Child):
    __slots__ = ("_value",)

    def __init__(self, state, labels_kv=()):
        super().__init__(state, labels_kv)
        self._value = 0.0

    def inc(self, v=1):
        if not self._state.enabled:
            return
        if v < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += v

    @property
    def value(self):
        return self._value


class Gauge(_Child):
    __slots__ = ("_value",)

    def __init__(self, state, labels_kv=()):
        super().__init__(state, labels_kv)
        self._value = 0.0

    def set(self, v):
        if not self._state.enabled:
            return
        with self._lock:
            self._value = float(v)

    def inc(self, v=1):
        if not self._state.enabled:
            return
        with self._lock:
            self._value += v

    def dec(self, v=1):
        self.inc(-v)

    @property
    def value(self):
        return self._value


class Histogram(_Child):
    """Cumulative-bucket histogram, `le` (<=) semantics like Prometheus.

    `observe(v, exemplar=...)` attaches an OpenMetrics-style exemplar —
    a trace id pinned to the bucket the value landed in — so a bad p99
    bucket links to the exact request trace that produced it. Last
    exemplar per bucket wins (bounded memory: at most one per bucket)."""

    __slots__ = ("_bounds", "_counts", "_sum", "_count", "_exemplars")

    def __init__(self, state, labels_kv=(), buckets=DEFAULT_BUCKETS):
        super().__init__(state, labels_kv)
        self._bounds = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self._bounds) + 1)   # last = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._exemplars = None     # lazily {bucket_idx: (trace_id, value)}

    def observe(self, v, exemplar=None):
        if not self._state.enabled:
            return
        with self._lock:
            idx = bisect.bisect_left(self._bounds, v)
            self._counts[idx] += 1
            self._sum += v
            self._count += 1
            if exemplar is not None:
                if self._exemplars is None:
                    self._exemplars = {}
                self._exemplars[idx] = (str(exemplar), float(v))

    def exemplars(self):
        """[(le, trace_id, value), ...] — the last exemplar recorded per
        bucket ('+Inf' for the overflow bucket)."""
        with self._lock:
            if not self._exemplars:
                return []
            out = []
            for idx in sorted(self._exemplars):
                le = (self._bounds[idx] if idx < len(self._bounds)
                      else "+Inf")
                tid, val = self._exemplars[idx]
                out.append((le, tid, val))
            return out

    @property
    def sum(self):
        return self._sum

    @property
    def count(self):
        return self._count

    def cumulative_buckets(self):
        """[(le, cumulative_count), ...] ending with ('+Inf', count)."""
        out, acc = [], 0
        for b, c in zip(self._bounds, self._counts):
            acc += c
            out.append((b, acc))
        out.append(("+Inf", self._count))
        return out


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Metric:
    """A named metric family: help text, declared label names, children."""

    def __init__(self, state, name, mtype, help_="", labelnames=(),
                 buckets=DEFAULT_BUCKETS):
        self.name = name
        self.type = mtype
        self.help = help_
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets)
        self._state = state
        self._lock = threading.Lock()
        self._children: dict[tuple, _Child] = {}
        if not self.labelnames:   # unlabeled: the family IS its one child
            self._children[()] = self._make(())

    def _make(self, labels_kv):
        cls = _TYPES[self.type]
        if self.type == "histogram":
            return cls(self._state, labels_kv, self.buckets)
        return cls(self._state, labels_kv)

    def labels(self, **kw):
        if set(kw) != set(self.labelnames):
            raise ValueError(
                f"{self.name} declares labels {self.labelnames}, "
                f"got {tuple(kw)}")
        key = tuple(sorted((k, str(v)) for k, v in kw.items()))
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    if len(self._children) >= MAX_LABEL_SETS:
                        raise ValueError(
                            f"{self.name}: label cardinality cap "
                            f"({MAX_LABEL_SETS}) exceeded — label values "
                            "must come from a small closed set")
                    child = self._make(key)
                    self._children[key] = child
        return child

    # unlabeled convenience: family forwards to its single child
    def _solo(self):
        if self.labelnames:
            raise ValueError(f"{self.name} requires .labels(...) — "
                             f"declared labels {self.labelnames}")
        return self._children[()]

    def inc(self, v=1):
        self._solo().inc(v)

    def set(self, v):
        self._solo().set(v)

    def dec(self, v=1):
        self._solo().dec(v)

    def observe(self, v, exemplar=None):
        self._solo().observe(v, exemplar)

    @property
    def value(self):
        return self._solo().value

    @property
    def sum(self):
        return self._solo().sum

    @property
    def count(self):
        return self._solo().count

    def cumulative_buckets(self):
        return self._solo().cumulative_buckets()

    def exemplars(self):
        return self._solo().exemplars()

    def children(self):
        with self._lock:
            return dict(self._children)


class MetricRegistry:
    """Process-wide metric table. get-or-create by name; re-registering
    with a conflicting type/labels/buckets raises (the no-drift contract
    tests/test_observability.py pins for the catalog)."""

    def __init__(self, enabled=None):
        self._state = _State(_env_default() if enabled is None else enabled)
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    # -- enable switch -------------------------------------------------------
    @property
    def enabled(self):
        return self._state.enabled

    def enable(self):
        self._state.enabled = True

    def disable(self):
        self._state.enabled = False

    # -- registration --------------------------------------------------------
    def _register(self, name, mtype, help_, labelnames, buckets):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.type != mtype or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.type} "
                        f"with labels {m.labelnames}; conflicting "
                        f"re-registration as {mtype} {tuple(labelnames)}")
                return m
            m = _Metric(self._state, name, mtype, help_, labelnames,
                        buckets)
            self._metrics[name] = m
            return m

    def counter(self, name, help_="", labels=()):
        return self._register(name, "counter", help_, labels,
                              DEFAULT_BUCKETS)

    def gauge(self, name, help_="", labels=()):
        return self._register(name, "gauge", help_, labels, DEFAULT_BUCKETS)

    def histogram(self, name, help_="", labels=(), buckets=DEFAULT_BUCKETS):
        return self._register(name, "histogram", help_, labels, buckets)

    def get(self, name):
        return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def collect(self):
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def reset(self):
        """Zero every series, keep definitions (tests; between bench rows)."""
        for m in self.collect():
            with m._lock:
                for key in list(m._children):
                    m._children[key] = m._make(key)
                if not m.labelnames and () not in m._children:
                    m._children[()] = m._make(())


# --------------------------------------------------------------------------
# exporters
# --------------------------------------------------------------------------

def _esc(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _label_str(labels_kv, extra=()):
    parts = [f'{k}="{_esc(v)}"' for k, v in (*labels_kv, *extra)]
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v):
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def to_prometheus_text(registry: MetricRegistry) -> str:
    """Prometheus exposition text (the /metrics page body)."""
    lines = []
    for m in registry.collect():
        if m.help:
            lines.append(f"# HELP {m.name} {_esc(m.help)}")
        lines.append(f"# TYPE {m.name} {m.type}")
        for key in sorted(m.children()):
            c = m.children()[key]
            if m.type == "histogram":
                # OpenMetrics exemplar suffixes ride the bucket lines the
                # exemplar landed in; plain-Prometheus scrapers treat the
                # '#' tail as a comment
                ex = {le: (tid, val) for le, tid, val in c.exemplars()}
                for le, n in c.cumulative_buckets():
                    ls = _label_str(key, (("le", _fmt(le) if le != "+Inf"
                                           else "+Inf"),))
                    suffix = ""
                    if le in ex:
                        tid, val = ex[le]
                        suffix = (f' # {{trace_id="{_esc(tid)}"}} '
                                  f"{_fmt(val)}")
                    lines.append(f"{m.name}_bucket{ls} {n}{suffix}")
                lines.append(f"{m.name}_sum{_label_str(key)} {_fmt(c.sum)}")
                lines.append(
                    f"{m.name}_count{_label_str(key)} {c.count}")
            else:
                lines.append(f"{m.name}{_label_str(key)} {_fmt(c.value)}")
    return "\n".join(lines) + "\n"


def snapshot(registry: MetricRegistry, meta=None) -> dict:
    """JSON-serializable snapshot of every series (bench rows embed this)."""
    metrics = []
    for m in registry.collect():
        samples = []
        for key in sorted(m.children()):
            c = m.children()[key]
            if m.type == "histogram":
                s = {"labels": dict(key), "sum": c.sum,
                     "count": c.count,
                     "buckets": [[le, n] for le, n in
                                 c.cumulative_buckets()]}
                ex = c.exemplars()
                if ex:
                    s["exemplars"] = [[le, tid, val] for le, tid, val in ex]
                samples.append(s)
            else:
                samples.append({"labels": dict(key), "value": c.value})
        metrics.append({"name": m.name, "type": m.type, "help": m.help,
                        "labelnames": list(m.labelnames),
                        "buckets": (list(m.buckets)
                                    if m.type == "histogram" else None),
                        "samples": samples})
    doc = {"format": SNAPSHOT_FORMAT, "recorded_unix": int(time.time()),
           "metrics": metrics}
    if meta:
        doc["meta"] = dict(meta)
    return doc


def load_snapshot(doc) -> MetricRegistry:
    """Rebuild a registry from snapshot() output (dict or JSON string) —
    the round-trip bench rows and tools/metrics_dump.py rely on."""
    if isinstance(doc, (str, bytes)):
        doc = json.loads(doc)
    if not isinstance(doc, dict) or doc.get("format") != SNAPSHOT_FORMAT:
        raise ValueError(f"not a metrics snapshot (format "
                         f"{SNAPSHOT_FORMAT} expected): {type(doc)}")
    reg = MetricRegistry(enabled=True)
    for m in doc.get("metrics", []):
        name, mtype = m["name"], m["type"]
        labelnames = tuple(m.get("labelnames") or ())
        if mtype == "histogram":
            fam = reg.histogram(name, m.get("help", ""), labelnames,
                                tuple(m.get("buckets") or DEFAULT_BUCKETS))
        elif mtype == "gauge":
            fam = reg.gauge(name, m.get("help", ""), labelnames)
        else:
            fam = reg.counter(name, m.get("help", ""), labelnames)
        for s in m.get("samples", []):
            child = fam.labels(**s["labels"]) if labelnames else fam._solo()
            if mtype == "histogram":
                cum = {(le if le == "+Inf" else float(le)): n
                       for le, n in s.get("buckets", [])}
                prev = 0
                for i, b in enumerate(child._bounds):
                    cur = cum.get(b, prev)
                    child._counts[i] = cur - prev
                    prev = cur
                child._count = int(s.get("count", prev))
                child._counts[-1] = child._count - prev
                child._sum = float(s.get("sum", 0.0))
                for le, tid, val in s.get("exemplars", []):
                    idx = (len(child._bounds) if le == "+Inf"
                           else child._bounds.index(float(le)))
                    if child._exemplars is None:
                        child._exemplars = {}
                    child._exemplars[idx] = (str(tid), float(val))
            else:
                child._value = float(s.get("value", 0.0))
    return reg


def write_snapshot_jsonl(path, registry: MetricRegistry, meta=None):
    """One header line + one line per metric family (append-friendly,
    same spirit as the bench ledger .bench_tpu_wins.jsonl)."""
    doc = snapshot(registry, meta)
    with open(path, "w") as f:
        f.write(json.dumps({"format": doc["format"],
                            "recorded_unix": doc["recorded_unix"],
                            **({"meta": doc["meta"]} if "meta" in doc
                               else {})}) + "\n")
        for m in doc["metrics"]:
            f.write(json.dumps(m) + "\n")
    return path


def read_snapshot_jsonl(path) -> dict:
    """Inverse of write_snapshot_jsonl: -> snapshot() dict."""
    with open(path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    if not lines or "format" not in lines[0]:
        raise ValueError(f"{path}: not a JSONL metrics snapshot")
    doc = dict(lines[0])
    doc["metrics"] = lines[1:]
    return doc


# --------------------------------------------------------------------------
# default (process-wide) registry
# --------------------------------------------------------------------------

_default_registry: MetricRegistry | None = None
_default_lock = threading.Lock()


def get_registry() -> MetricRegistry:
    global _default_registry
    if _default_registry is None:
        with _default_lock:
            if _default_registry is None:
                _default_registry = MetricRegistry()
    return _default_registry
