"""Flight recorder: a bounded ring of typed engine events, dumped on crash.

The "black box" for the serving runbook. The engine appends tiny typed
events (dispatch/readback with lane epochs, membership changes, fault
fires, shed/timeout/backpressure, compile-cache hits/misses) into a
PREALLOCATED ring — recording is a slot assignment under a lock, no
growth — and on an unhandled exception, a SIGTERM preemption, or a
chaos-drill escape path the last N events are dumped to a postmortem
JSON an operator (or tools/chaos_drill.py) can read.

STANDALONE like metrics.py/tracing.py: stdlib only, loadable via
importlib.util.spec_from_file_location outside the package. The
`flight_recorder_dumps_total` catalog counter is wired through a
guarded import so standalone loads simply skip it.

Disabled-mode contract (same as the metrics registry): every mutation
starts with one attribute check and returns before touching the ring,
so a disabled recorder allocates nothing on the hot path — callers that
would build kwargs dicts must guard with `if rec.enabled:` themselves
(argument packing happens at the call site, before we can bail).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

__all__ = ["FlightRecorder", "EVENT_KINDS", "get_recorder",
           "default_dump_path", "validate_dump", "install_crash_handlers",
           "DUMP_FORMAT", "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 4096
DUMP_FORMAT = 1

# The closed set of event kinds (catalog discipline, like FAULT_SITES):
# recording an unknown kind raises, so the dump schema in
# OBSERVABILITY.md and validate_dump() below can enumerate them.
EVENT_KINDS = {
    "dispatch": "decode tile launched (tile id, lanes, epochs, k)",
    "readback": "decode tile device->host readback drained",
    "membership": "decode lane set changed (lane retired/admitted, "
                  "device state re-uploaded)",
    "admit": "request admitted to a lane",
    "finish": "request finished (reason: eos|length|error|timeout|shed)",
    "shed": "request shed under pressure (requeued or rejected)",
    "timeout": "request deadline expired",
    "backpressure": "admission rejected: queue at capacity",
    "fault": "fault-injection site fired (site, hit number)",
    "preempt": "SIGTERM preemption acknowledged by the supervisor",
    "compile_cache": "PIR compile-cache probe (hit|miss|corrupt|store)",
    "pir_pipeline": "PIR pass pipeline ran (pass count, cache status)",
    "retry": "resilient retry of a transient failure",
    "degrade": "serving runtime permanently dropped a feature "
               "(speculation_off | kv_bf16 | sched_fifo) after a fault, "
               "or degraded one prefix-cache op to a miss (prefix_miss)",
    "prefix_hit": "admission resolved leading paged-KV blocks from the "
                  "cross-request prefix cache (rid, tokens, blocks)",
    "sched": "SLO scheduler action (brownout level transition, lane "
             "preempt/resume, best_effort shed)",
    "error": "unhandled error captured by a crash handler",
    "note": "free-form marker (drills, tests)",
    "profile": "profiler/loadgen summary (phase coverage, scenario, "
               "goodput) recorded at the end of a harness run",
    "mesh": "serving-mesh action (route pick, paged-KV handoff, "
            "replica failover/tombstone) with the request trace id so "
            "cross-replica timelines join",
    "controller": "mesh autoscale controller action (scale_up spawn, "
                  "drain_begin, scale_down retire, drain_forced kill, "
                  "latch_off back to advisory-only)",
    "adapter": "adapter store lifecycle (hot-load into a pool slot, "
               "LRU evict of an idle slot, typed admission reject on a "
               "store fault) with the adapter name and slot id",
}


def default_dump_path():
    """Where postmortems land: $FLAGS_flight_recorder_dir (or the
    tempdir) / flight-<pid>-<monotonic-ish>.json."""
    root = os.environ.get("FLAGS_flight_recorder_dir") or tempfile.gettempdir()
    return os.path.join(
        root, f"flight-{os.getpid()}-{time.time_ns() // 1_000_000}.json")


class FlightRecorder:
    """Bounded ring of typed events. `capacity` slots are preallocated;
    record() overwrites the oldest once full (seq keeps total order)."""

    __slots__ = ("enabled", "capacity", "_buf", "_seq", "_lock",
                 "_dumps", "_t0_ns")

    def __init__(self, enabled=False, capacity=DEFAULT_CAPACITY):
        self.enabled = bool(enabled)
        self.capacity = int(capacity)
        if self.capacity <= 0:
            raise ValueError("capacity must be positive")
        self._buf = [None] * self.capacity     # preallocated ring
        self._seq = 0                          # total events ever recorded
        self._lock = threading.Lock()
        self._dumps = 0
        self._t0_ns = time.monotonic_ns()

    # -- recording -----------------------------------------------------------
    def record(self, kind, **fields):
        """Append one event; slot assignment only, never grows. The
        disabled fast path is the first line — but note **fields packs a
        dict at the call site, so hot loops guard externally with
        `if rec.enabled:` before building arguments."""
        if not self.enabled:
            return
        if kind not in EVENT_KINDS:
            raise KeyError(f"unknown flight-recorder event kind {kind!r}; "
                           f"registered kinds: {sorted(EVENT_KINDS)}")
        t_ms = (time.monotonic_ns() - self._t0_ns) // 1_000_000
        with self._lock:
            seq = self._seq
            self._seq += 1
            self._buf[seq % self.capacity] = (seq, t_ms, kind,
                                              fields or None)

    def enable(self):
        self.enabled = True

    def disable(self):
        self.enabled = False

    def clear(self):
        with self._lock:
            self._buf = [None] * self.capacity
            self._seq = 0
            self._t0_ns = time.monotonic_ns()

    # -- inspection ----------------------------------------------------------
    def __len__(self):
        with self._lock:
            return min(self._seq, self.capacity)

    @property
    def total_recorded(self):
        return self._seq

    @property
    def dumps(self):
        return self._dumps

    def events(self):
        """Events oldest->newest as dicts (the dump's `events` shape)."""
        with self._lock:
            seq = self._seq
            start = max(0, seq - self.capacity)
            raw = [self._buf[i % self.capacity] for i in range(start, seq)]
        out = []
        for ev in raw:
            if ev is None:      # racing writer mid-wrap; skip the hole
                continue
            s, t_ms, kind, fields = ev
            d = {"seq": s, "t_ms": t_ms, "kind": kind}
            if fields:
                d.update(fields)
            out.append(d)
        return out

    def counts_by_kind(self):
        out = {}
        for ev in self.events():
            out[ev["kind"]] = out.get(ev["kind"], 0) + 1
        return out

    # -- postmortem ----------------------------------------------------------
    def dump(self, path=None, reason="manual", extra=None):
        """Write the postmortem JSON; returns the path. Dumping works
        even when recording is disabled (the dump then documents an
        empty ring — still evidence the crash handler ran)."""
        path = path or default_dump_path()
        events = self.events()
        doc = {
            "format": DUMP_FORMAT,
            "reason": str(reason),
            "pid": os.getpid(),
            "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "capacity": self.capacity,
            "total_recorded": self._seq,
            "dropped": max(0, self._seq - self.capacity),
            "events": events,
        }
        if extra:
            doc["extra"] = dict(extra)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        os.replace(tmp, path)
        self._dumps += 1
        try:        # guarded: absent in standalone loads
            from .catalog import metric
            metric("flight_recorder_dumps_total", reason=str(reason)).inc()
        except Exception:   # noqa: BLE001 — a postmortem never fails on metrics
            pass
        return path


_REQUIRED_EVENT_KEYS = ("seq", "t_ms", "kind")


def validate_dump(path):
    """Schema-check a postmortem file; returns the parsed dict or raises
    ValueError describing the corruption. tools/chaos_drill.py gates its
    exit code on this."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: dump is not a JSON object")
    if doc.get("format") != DUMP_FORMAT:
        raise ValueError(f"{path}: unknown dump format {doc.get('format')!r}")
    for key in ("reason", "pid", "capacity", "total_recorded", "events"):
        if key not in doc:
            raise ValueError(f"{path}: missing required key {key!r}")
    if not isinstance(doc["events"], list):
        raise ValueError(f"{path}: 'events' is not a list")
    prev_seq = -1
    for i, ev in enumerate(doc["events"]):
        if not isinstance(ev, dict):
            raise ValueError(f"{path}: events[{i}] is not an object")
        for key in _REQUIRED_EVENT_KEYS:
            if key not in ev:
                raise ValueError(f"{path}: events[{i}] missing {key!r}")
        if ev["kind"] not in EVENT_KINDS:
            raise ValueError(
                f"{path}: events[{i}] has unknown kind {ev['kind']!r}")
        if not isinstance(ev["seq"], int) or ev["seq"] <= prev_seq:
            raise ValueError(
                f"{path}: events[{i}] seq {ev['seq']!r} not increasing")
        prev_seq = ev["seq"]
    return doc


# --------------------------------------------------------------------------
# default (process-wide) recorder + crash handlers
# --------------------------------------------------------------------------

_default_recorder: FlightRecorder | None = None
_default_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    global _default_recorder
    if _default_recorder is None:
        with _default_lock:
            if _default_recorder is None:
                _default_recorder = FlightRecorder(
                    enabled=os.environ.get("FLAGS_observability", "")
                    .lower() in ("1", "true", "yes", "on"))
    return _default_recorder


_hooks_installed = False


def install_crash_handlers():
    """Chain sys.excepthook so an unhandled exception dumps the black
    box before the traceback prints. Idempotent. (SIGTERM preemption
    dumps are wired by the resilience supervisor, which owns that
    signal; doing both here would fight over the handler.)"""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        rec = get_recorder()
        try:
            rec.record("error", exc_type=exc_type.__name__, msg=str(exc)[:200])
            rec.dump(reason="unhandled_error")
        except Exception:   # noqa: BLE001 — never mask the real traceback
            pass
        prev(exc_type, exc, tb)

    sys.excepthook = hook
