"""StepWatch: training-loop telemetry hook.

reference capability: python/paddle/profiler/timer.py Benchmark (ips /
step cost) grown into the always-on telemetry the ROADMAP's production
system needs: per-step wall time (with optional phase breakdown), online
tokens/s + MFU, loss / grad-norm gauges, and a JSONL step log whose rows
carry the same round/provenance fields as the bench ledger
(.bench_tpu_wins.jsonl), so training evidence and bench evidence are one
schema.

Zero-cost when disabled: step() checks the registry's enable flag first
and returns — the 50-step smoke-loop overhead guard in
tests/test_observability.py pins this.
"""

from __future__ import annotations

import json
import os
import time

from . import metrics as _metrics
from .catalog import metric as _metric

__all__ = ["StepWatch", "current_round"]


def current_round(repo_dir=None):
    """Round number from the driver's PROGRESS.jsonl heartbeat (None if
    unavailable) — same provenance scoping as bench._current_round."""
    try:
        path = os.path.join(repo_dir or os.getcwd(), "PROGRESS.jsonl")
        last = None
        with open(path) as f:
            for line in f:
                if line.strip():
                    last = line
        obj = json.loads(last)
        return obj.get("round") if isinstance(obj, dict) else None
    except Exception:
        return None


class StepWatch:
    """
    sw = StepWatch(tokens_per_step=batch*seq,
                   flops_per_token=6*n_params, peak_flops=197e12,
                   jsonl_path="steps.jsonl", run_name="llama_1.3b")
    sw.start()
    for batch in loader:
        with sw.phase("data"):
            x, y = next(it)
        loss = train_step(x, y)             # rest of the step is "compute"
        sw.step(loss=float(loss))
    """

    def __init__(self, tokens_per_step=None, flops_per_token=None,
                 peak_flops=None, jsonl_path=None, run_name="train",
                 round=None, provenance=None, log_every=1):
        self.tokens_per_step = tokens_per_step
        self.flops_per_token = flops_per_token
        self.peak_flops = peak_flops
        self.jsonl_path = jsonl_path
        self.run_name = run_name
        self.round = round if round is not None else current_round()
        self.provenance = provenance
        self.log_every = max(int(log_every), 1)
        self._registry = _metrics.get_registry()
        self._m_step = _metric("train_step_seconds")
        self._m_tokens = _metric("train_tokens_total")
        self._m_loss = _metric("train_loss")
        self._m_gnorm = _metric("train_grad_norm")
        self._m_tps = _metric("train_tokens_per_s")
        self._m_mfu = _metric("train_mfu")
        self._i = 0
        self._t_last = None
        self._phases = {}
        self._durs = []

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        self._t_last = time.perf_counter()
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        return False

    # -- phase breakdown -----------------------------------------------------
    class _Phase:
        __slots__ = ("_sw", "_name", "_t0")

        def __init__(self, sw, name):
            self._sw = sw
            self._name = name

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._sw._phases[self._name] = (
                self._sw._phases.get(self._name, 0.0)
                + time.perf_counter() - self._t0)
            return False

    def phase(self, name):
        """Accumulate a named slice of the current step (data/compute/...)."""
        if not self._registry.enabled:
            from .tracing import _NOOP
            return _NOOP
        return StepWatch._Phase(self, name)

    # -- per-step hook -------------------------------------------------------
    def step(self, loss=None, grad_norm=None, tokens=None):
        """Close the current step. Call AFTER the host has synced (e.g.
        after float(loss)) or the 'step time' is only dispatch time."""
        if not self._registry.enabled:
            return None
        now = time.perf_counter()
        if self._t_last is None:
            self._t_last = now
            return None
        dt = now - self._t_last
        self._t_last = now
        self._i += 1
        ntok = tokens if tokens is not None else self.tokens_per_step
        row = self._emit(self._i, dt, ntok, loss, grad_norm,
                         breakdown=self._phases or None)
        self._phases = {}
        return row

    def record_run(self, steps, seconds, tokens=None, loss=None,
                   grad_norm=None):
        """Aggregate entry for an externally timed region (bench.py times
        its loop without per-step syncs; feeding those per-step would
        record dispatch time, not step time)."""
        if not self._registry.enabled or steps <= 0:
            return None
        dt = seconds / steps
        ntok = (tokens / steps if tokens is not None
                else self.tokens_per_step)
        row = None
        for _ in range(int(steps)):
            self._i += 1
            row = self._emit(self._i, dt, ntok, loss, grad_norm,
                             aggregated=True)
        return row

    def _emit(self, i, dt, ntok, loss, grad_norm, breakdown=None,
              aggregated=False):
        self._durs.append(dt)
        del self._durs[:-1000]
        self._m_step.observe(dt)
        row = {"event": "step", "run": self.run_name, "step": i,
               "step_time_s": dt, "round": self.round,
               "recorded_unix": int(time.time())}
        if aggregated:
            row["aggregated"] = True
        if self.provenance:
            row["provenance"] = self.provenance
        if breakdown:
            row["breakdown_s"] = {k: round(v, 6)
                                  for k, v in breakdown.items()}
        if ntok:
            tps = ntok / dt
            self._m_tokens.inc(ntok)
            self._m_tps.set(tps)
            row["tokens_per_s"] = tps
            if self.flops_per_token and self.peak_flops:
                mfu = self.flops_per_token * tps / self.peak_flops
                self._m_mfu.set(mfu)
                row["mfu"] = round(mfu, 6)
        if loss is not None:
            self._m_loss.set(loss)
            row["loss"] = float(loss)
        if grad_norm is not None:
            self._m_gnorm.set(grad_norm)
            row["grad_norm"] = float(grad_norm)
        if self.jsonl_path and (i % self.log_every == 0):
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(row) + "\n")
        return row

    # -- reporting -----------------------------------------------------------
    def summary(self):
        if not self._durs:
            return {"steps": 0}
        n = len(self._durs)
        avg = sum(self._durs) / n
        out = {"steps": self._i, "avg_step_time_s": avg}
        if self.tokens_per_step:
            out["tokens_per_s"] = self.tokens_per_step / avg
            if self.flops_per_token and self.peak_flops:
                out["mfu"] = (self.flops_per_token * out["tokens_per_s"]
                              / self.peak_flops)
        return out
