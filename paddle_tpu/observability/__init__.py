"""Unified runtime observability: metrics registry, span tracing, SLO
telemetry — one substrate for train / serve / bench evidence.

reference capability: the reference's runtime evidence is split across
the profiler host-event table (python/paddle/profiler/), timer.py
throughput benchmarks, and per-tool logs. Here a single always-on layer
feeds every consumer: `MetricRegistry` (Counter/Gauge/Histogram with
labels; Prometheus text + JSONL snapshot exporters), a span `Tracer`
(monotonic clocks, parent/child nesting, Chrome-trace export that also
backs profiler.export_chrome_tracing), and `StepWatch` training
telemetry (step time, online tokens/s + MFU, bench-ledger-schema JSONL).

Disabled by default — `FLAGS_observability` (env or paddle.set_flags)
or `observability.enable()` turns it on. Every mutation has a no-op
fast path (one attribute check, zero allocation) so tier-1 timing and
TPU step time are unaffected when off.

Instrumented hot paths: inference/serving.py (TTFT, TPOT, queue depth,
occupancy, pool gauge, admission counters), generation.generate,
ops/pallas/attention_router (decision-source counters), bench.py (rows
embed registry snapshots), distributed elastic recovery (restart/resume
counters). The canonical metric-name catalog lives in catalog.py and is
documented in OBSERVABILITY.md (drift is test-pinned).
"""

from __future__ import annotations

from . import (  # noqa: F401
    autoscale, catalog, export, federation, metrics, quantiles, recorder,
    slo, timeseries, tracing)
from .autoscale import AutoscaleAdvisor  # noqa: F401
from .catalog import CATALOG, metric, register_all  # noqa: F401
from .federation import MeshCollector  # noqa: F401
from .export import prometheus_text, snapshot  # noqa: F401
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricRegistry, get_registry,
    load_snapshot, to_prometheus_text)
from .quantiles import (  # noqa: F401
    quantile_from_cumulative, quantiles_from_cumulative)
from .recorder import FlightRecorder, get_recorder  # noqa: F401
from .slo import DEFAULT_SLOS, SLOEngine, SLOSpec  # noqa: F401
from .stepwatch import StepWatch, current_round  # noqa: F401
from .timeseries import (  # noqa: F401
    RECORDING_RULES, MetricsSampler, Series)
from .tracing import (  # noqa: F401
    Tracer, get_tracer, new_trace_id, span, trace)

__all__ = ["enable", "disable", "enabled", "MetricRegistry", "Counter",
           "Gauge", "Histogram", "get_registry", "snapshot",
           "to_prometheus_text", "load_snapshot", "Tracer", "get_tracer",
           "span", "trace", "new_trace_id", "StepWatch", "current_round",
           "CATALOG", "metric", "register_all", "FlightRecorder",
           "get_recorder", "SLOEngine", "SLOSpec", "DEFAULT_SLOS",
           "quantile_from_cumulative", "quantiles_from_cumulative",
           "MetricsSampler", "Series", "RECORDING_RULES", "MeshCollector",
           "AutoscaleAdvisor", "autoscale", "catalog", "export",
           "federation", "metrics", "quantiles", "recorder", "slo",
           "timeseries", "tracing"]


def _count_dropped(n):
    # tracing.py is standalone and cannot name the catalog itself; the
    # package wires the ring-wrap casualties into the metric here
    try:
        metric("tracer_dropped_spans_total").inc(n)
    except Exception:  # noqa: BLE001 — tracing never raises
        pass


def enable():
    """Turn the whole layer on (metrics + spans + recorder)."""
    get_registry().enable()
    tr = get_tracer()
    tr.enable()
    if tr.on_drop is None:
        tr.on_drop = _count_dropped
    get_recorder().enable()


def disable():
    get_registry().disable()
    get_tracer().disable()
    get_recorder().disable()


def enabled() -> bool:
    return get_registry().enabled


def _sync_with_flag():
    """Honor FLAGS_observability at import and via paddle.set_flags (the
    flags registry calls back into this module on set)."""
    try:
        from ..framework import flags as _flags
        v = _flags.flag_value("observability")
    except Exception:
        return
    s = str(v).lower()
    if s in ("1", "true", "yes", "on"):
        enable()
    elif s in ("0", "false", "no", "off"):
        disable()


_sync_with_flag()
