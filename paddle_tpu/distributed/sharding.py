"""Group sharded (ZeRO) entry points.

reference: python/paddle/distributed/sharding/group_sharded.py
(group_sharded_parallel wrapping stage2/stage3 from
fleet/meta_parallel/sharding/).

TPU-native ZeRO: optimizer states / grads / params are arrays — stage N is a
sharding spec on those arrays over the 'sharding' mesh axis. The real
implementation is parallel.SpmdTrainer(sharding_stage=1/2/3), which keeps
the partition inside the jitted step (opt-state partition at stage 1, grad
reduce-scatter at stage 2, param partition with gather-on-use at stage 3).
This facade keeps the reference's one-call eager API on top of the
steady-state eager fallback in fleet.meta_optimizers.
"""

from __future__ import annotations

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm=False,
                           dp_group=None, exclude_layer=None):
    from .fleet.meta_optimizers import ShardingOptimizerStage1
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}.get(level, 1)
    sharded_opt = ShardingOptimizerStage1(optimizer, stage=stage, group=group)
    if scaler is not None:
        return model, sharded_opt, scaler
    return model, sharded_opt, scaler


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ..framework.io_file import save
    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
