"""Auto-tuner: search dp/mp/pp/sharding/micro-batch/remat configurations.

reference: python/paddle/distributed/auto_tuner/tuner.py (AutoTuner,
search_once/add_cfg history loop), prune.py (prune_by_mp/pp/mbs/sharding/
recompute/memory), search.py (GridSearch).

TPU-native design: the reference launches a fresh multi-GPU job per
candidate and prunes with rules + an allocator-reported memory model. Here
candidates are mesh factorizations of the TPU slice; pruning combines the
same divisibility rules with an analytic HBM model (params/grads/optimizer
state under the chosen ZeRO stage + activation footprint under remat), and
ranking uses an analytic step-time model (MXU FLOPs + ICI collective bytes
+ pipeline bubble). A `measure_fn` hook lets callers time real trials
(SpmdTrainer / LlamaPipeRunner steps) exactly like the reference's launch
loop — search_once()/add_cfg() keep that protocol.
"""

from __future__ import annotations

import itertools

__all__ = ["AutoTuner", "TunerConfig"]


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


class TunerConfig:
    """User knobs (reference tuner_cfg keys kept where they exist)."""

    def __init__(self, num_devices, global_batch_size, num_layers,
                 hidden_size, num_attention_heads, seq_length, vocab_size,
                 hbm_bytes=16e9, peak_flops=197e12, ici_bandwidth=4.5e10,
                 dtype_bytes=2, max_mp=None, max_pp=None,
                 candidates=None, task_limit=100):
        self.num_devices = num_devices
        self.global_batch_size = global_batch_size
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.num_attention_heads = num_attention_heads
        self.seq_length = seq_length
        self.vocab_size = vocab_size
        self.hbm_bytes = float(hbm_bytes)
        self.peak_flops = float(peak_flops)
        self.ici_bandwidth = float(ici_bandwidth)
        self.dtype_bytes = dtype_bytes
        self.max_mp = max_mp or num_devices
        self.max_pp = max_pp or num_devices
        self.candidates = candidates or {}
        self.task_limit = task_limit

    # approximate decoder parameter count (attention + MLP + embeddings)
    def n_params(self):
        h, L, v = self.hidden_size, self.num_layers, self.vocab_size
        return L * (12 * h * h) + 2 * v * h


class AutoTuner:
    """Grid search with pruning over (dp, mp, pp, sharding_stage,
    micro_batch_size, recompute)."""

    PRUNE_RULES = ("mp", "pp", "mbs", "sharding", "memory")

    def __init__(self, tuner_cfg: TunerConfig, measure_fn=None):
        self.cfg = tuner_cfg
        self.measure_fn = measure_fn
        self.history_cfgs = []
        self.pruned_cfgs = []
        self._queue = self._build_candidates()
        self._issued = 0  # queue position of the next un-returned candidate
        self.cur_task_id = 0

    # -- candidate generation (reference: utils.default_candidates) --------
    def _build_candidates(self):
        c = self.cfg
        cand = c.candidates
        mps = cand.get("mp_degree") or [
            d for d in _divisors(c.num_devices) if d <= c.max_mp]
        pps = cand.get("pp_degree") or [
            d for d in _divisors(c.num_devices) if d <= c.max_pp]
        stages = cand.get("sharding_stage") or [0, 1, 2, 3]
        mbss = cand.get("micro_batch_size") or _divisors(
            c.global_batch_size)
        remats = cand.get("use_recompute") or [False, True]

        out = []
        for mp, pp, stage, mbs, remat in itertools.product(
                mps, pps, stages, mbss, remats):
            if c.num_devices % (mp * pp) != 0:
                continue
            rest = c.num_devices // (mp * pp)
            shd = rest if stage > 0 else 1
            dp = rest // shd
            cfgd = dict(dp_degree=dp, mp_degree=mp, pp_degree=pp,
                        sharding_degree=shd, sharding_stage=stage,
                        micro_batch_size=mbs, use_recompute=remat)
            reason = self._prune(cfgd)
            if reason:
                cfgd["pruned_reason"] = reason
                self.pruned_cfgs.append(cfgd)
                continue
            cfgd["estimated_step_time"] = self._cost(cfgd)
            out.append(cfgd)
        out.sort(key=lambda d: d["estimated_step_time"])
        return out[: c.task_limit]

    # -- pruning (reference: prune.py registered rules) --------------------
    def _prune(self, d):
        c = self.cfg
        mp, pp = d["mp_degree"], d["pp_degree"]
        dp, shd = d["dp_degree"], d["sharding_degree"]
        mbs = d["micro_batch_size"]
        if c.num_attention_heads % mp or c.hidden_size % mp:
            return f"mp {mp} does not divide heads/hidden"  # prune_by_mp
        if c.num_layers % pp:
            return f"pp {pp} does not divide layers"        # prune_by_pp
        if dp == 0 or c.global_batch_size % (dp * max(shd, 1)):
            return "global batch not divisible by dp*sharding"
        local_batch = c.global_batch_size // (dp * max(shd, 1))
        if local_batch % mbs:
            return f"micro batch {mbs} does not divide local batch"
        n_micro = local_batch // mbs
        if pp > 1 and n_micro < pp:
            return f"pipeline needs microbatches >= pp ({n_micro} < {pp})"
        mem = self._memory_bytes(d)
        if mem > c.hbm_bytes:
            return (f"memory model {mem / 1e9:.1f}GB exceeds HBM "
                    f"{c.hbm_bytes / 1e9:.1f}GB")  # prune_by_memory
        return None

    # -- analytic per-device memory model ----------------------------------
    def _memory_bytes(self, d):
        c = self.cfg
        P = c.n_params()
        mp, pp, shd = d["mp_degree"], d["pp_degree"], d["sharding_degree"]
        stage = d["sharding_stage"]
        shard_p = P / (mp * pp)
        params = shard_p * c.dtype_bytes / (shd if stage >= 3 else 1)
        grads = shard_p * c.dtype_bytes / (shd if stage >= 2 else 1)
        opt = shard_p * 8 / (shd if stage >= 1 else 1)  # fp32 m+v
        mbs, s, h = d["micro_batch_size"], c.seq_length, c.hidden_size
        layers_local = c.num_layers // pp
        if d["use_recompute"]:
            act_per_layer = 2 * s * h * c.dtype_bytes        # boundary only
        else:
            act_per_layer = 34 * s * h * c.dtype_bytes / 2   # full residuals
        live_mb = min(2 * pp - 1, max(
            c.global_batch_size // (d["dp_degree"] * max(shd, 1) * mbs), 1)) \
            if pp > 1 else 1
        acts = mbs * layers_local * act_per_layer * live_mb
        return params + grads + opt + acts

    # -- analytic step-time cost (ranking only; relative, seconds-ish) -----
    def _cost(self, d):
        c = self.cfg
        P = c.n_params()
        tokens = c.global_batch_size * c.seq_length
        flops = 6.0 * P * tokens
        if d["use_recompute"]:
            flops *= 4 / 3          # one extra forward
        compute = flops / (c.num_devices * c.peak_flops * 0.5)
        # mp all-reduces: ~4 activations of (tokens/dp/shd, h) per layer
        mp, pp = d["mp_degree"], d["pp_degree"]
        dp, shd = d["dp_degree"], d["sharding_degree"]
        comm = 0.0
        if mp > 1:
            bytes_mp = (4 * c.num_layers
                        * (tokens / (dp * max(shd, 1))) * c.hidden_size
                        * c.dtype_bytes * 2 * (mp - 1) / mp)
            comm += bytes_mp / c.ici_bandwidth
        if dp * max(shd, 1) > 1:
            # grad reduce: 2 bytes/param ring all-reduce (or reduce-scatter)
            comm += (P / (mp * pp)) * c.dtype_bytes * 2 / c.ici_bandwidth
        bubble = 0.0
        if pp > 1:
            local_batch = c.global_batch_size // (dp * max(shd, 1))
            m = max(local_batch // d["micro_batch_size"], 1)
            bubble = compute * (pp - 1) / (m + pp - 1)
        return compute + comm + bubble

    # -- reference search protocol -----------------------------------------
    def search_once(self):
        """Next un-run candidate (reference: tuner.py search_once), or None.
        Issued candidates are tracked by queue position — measured/extra
        keys added by the caller never affect the walk."""
        if self._issued >= len(self._queue):
            return None
        cfgd = self._queue[self._issued]
        self._issued += 1
        self.cur_task_id += 1
        return dict(cfgd)

    def add_cfg(self, cfg):
        """Record a run config (+ measured metrics if the caller added them)."""
        self.history_cfgs.append(
            {k: v for k, v in cfg.items() if k != "estimated_step_time"}
            | {"estimated_step_time": cfg.get("estimated_step_time")})

    def search_all(self):
        """All surviving candidates, best-estimated first."""
        return [dict(d) for d in self._queue]

    def tune(self, max_trials=None):
        """Full loop: measure each candidate with measure_fn (step-time
        seconds; may raise to mark infeasible) and return the best."""
        best = None
        trials = 0
        while True:
            if max_trials and trials >= max_trials:
                break  # before search_once: don't pop-and-drop a candidate
            cur = self.search_once()
            if cur is None:
                break
            trials += 1
            if self.measure_fn is not None:
                try:
                    cur["measured_step_time"] = float(self.measure_fn(cur))
                except Exception as e:  # infeasible (OOM/compile): record
                    cur["error"] = f"{type(e).__name__}: {e}"
                    self.add_cfg(cur)
                    continue
            self.add_cfg(cur)
            key = cur.get("measured_step_time",
                          cur.get("estimated_step_time"))
            if best is None or key < best[0]:
                best = (key, cur)
        return best[1] if best else None
