"""Pipeline model container.

reference: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py — PipelineLayer:257, LayerDesc:56, SharedLayerDesc:76,
segmentation :207.

TPU-native: PipelineLayer keeps the stage segmentation logic (cut a layer
list into pp_degree stages) but stages become slices of a scanned/stacked
weight structure executed by the compiled 1F1B schedule in
pipeline_parallel.py rather than per-process partitions.
"""

from __future__ import annotations

import numpy as np

from ....nn.layer.layers import Layer, LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Tied layers (e.g. embedding/unembedding). Under a single controller
    weight tying is plain Python object sharing — no cross-stage broadcast."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """reference: pp_layers.py:257."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._num_stages = num_stages or 1
        self._recompute_interval = recompute_interval
        self._shared = {}
        self._descs = list(layers)
        built = []
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    layer = self._shared[d.layer_name]
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                built.append((layer, d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, Layer):
                built.append((d, None))
            elif callable(d):
                built.append((d, None))
            else:
                raise TypeError(f"bad pipeline entry {d!r}")
        self.run_function = built
        layer_objs = [l for l, _ in built if isinstance(l, Layer)]
        self._layers_list = LayerList(layer_objs)
        # stage segmentation (uniform by layer count, like seg_method='uniform')
        n = len(built)
        per = int(np.ceil(n / self._num_stages))
        self._stage_bounds = [(i * per, min((i + 1) * per, n))
                              for i in range(self._num_stages)]

    @property
    def num_stages(self):
        return self._num_stages

    def get_stage_fns(self):
        """Return one callable per stage (composition of its segment)."""
        fns = []
        for lo, hi in self._stage_bounds:
            seg = self.run_function[lo:hi]

            def stage_fn(x, _seg=seg):
                for layer, ffn in _seg:
                    if ffn is not None:
                        x = ffn(layer, x)
                    elif isinstance(layer, Layer) or callable(layer):
                        x = layer(x)
                return x

            fns.append(stage_fn)
        return fns

    def forward(self, input):
        x = input
        for layer, ffn in self.run_function:
            if ffn is not None:
                x = ffn(layer, x)
            else:
                x = layer(x)
        return x
