"""Pipeline-parallel runtime.

reference: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py
— PipelineParallel:255, 1F1B schedule (forward_backward_pipeline:575), P2P
via SendRecvMeta/batched isend_irecv (pp_utils/p2p_communication.py).

TPU-native: there are no per-stage OS processes to p2p between — the
schedule is compiled. This wrapper implements the micro-batch loop with
gradient accumulation (the semantics of 1F1B from the optimizer's view:
identical gradients); the compiled multi-chip schedule (stage loop over a
'pp' mesh axis with lax.ppermute activations transfers) lives in
paddle_tpu.parallel.pipeline and is what dryrun_multichip exercises.
"""

from __future__ import annotations

import numpy as np

from ....framework.core import Tensor
from ....nn.layer.layers import Layer

__all__ = ["PipelineParallel"]


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self._hcg = hcg
        cfg = getattr(strategy, "pipeline_configs", {}) or {}
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1) or 1)
        self.micro_batch_size = int(cfg.get("micro_batch_size", 1) or 1)
        self.total_loss = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _split_micro(self, data):
        if isinstance(data, (tuple, list)):
            xs, ys = data
        else:
            xs, ys = data, None
        n = self.accumulate_steps
        micro = []
        bs = xs.shape[0]
        mbs = max(bs // n, 1)
        for i in range(0, bs, mbs):
            x_i = xs[i:i + mbs]
            y_i = ys[i:i + mbs] if ys is not None else None
            micro.append((x_i, y_i))
        return micro

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """reference: pipeline_parallel.py:train_batch — returns mean loss."""
        self._layers.train()
        micro = self._split_micro(data)
        total = None
        loss_fn = getattr(self._layers, "_loss_fn", None)
        for x_i, y_i in micro:
            out = self._layers(x_i)
            loss = loss_fn(out, y_i) if loss_fn is not None else out
            scaled = loss / len(micro)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = loss.detach() if total is None else total + loss.detach()
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total / len(micro)

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        micro = self._split_micro(data)
        loss_fn = getattr(self._layers, "_loss_fn", None)
        total = None
        from ....framework.core import no_grad
        with no_grad():
            for x_i, y_i in micro:
                out = self._layers(x_i)
                loss = loss_fn(out, y_i) if (loss_fn and compute_loss) else out
                total = loss if total is None else total + loss
        return total / len(micro)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)
