"""Meta-parallel wrappers + TP layers.

reference: python/paddle/distributed/fleet/meta_parallel/ and
fleet/layers/mpu/mp_layers.py.
"""

from .parallel_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy, TensorParallel, ShardingParallel, SegmentParallel,
)
from .pp_layers import PipelineLayer, LayerDesc, SharedLayerDesc  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401
