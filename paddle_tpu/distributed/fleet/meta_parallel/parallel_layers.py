"""Tensor-parallel (Megatron-style) layers, GSPMD edition.

reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:47, ColumnParallelLinear:334, RowParallelLinear:541,
ParallelCrossEntropy:742; collectives in mp_ops.py (_c_identity/_mp_allreduce).

TPU-native: instead of manually slicing weights per rank and issuing NCCL
allreduces, each layer annotates its weight with a NamedSharding over the
"mp" mesh axis and constrains its activations; XLA/GSPMD partitions the
matmul and inserts the all-reduce/all-gather on ICI. The math and the
communication pattern are identical to Megatron — the code is 10x smaller.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ....framework.core import Tensor, execute
from ....nn import functional as F
from ....nn.layer.layers import Layer
from ....nn import initializer as I

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy", "TensorParallel",
           "ShardingParallel", "SegmentParallel"]


def _mp_mesh():
    from .. import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return None
    return hcg.mesh


def _constrain(x, spec):
    """with_sharding_constraint under trace; device_put-free no-op eagerly."""
    mesh = _mp_mesh()
    if mesh is None:
        return x

    def f(a):
        from ....framework import core as _core
        if _core.in_trace():
            return jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, P(*spec)))
        return a

    return execute(f, x, _name="sharding_constraint")


def _shard_param(p, spec):
    mesh = _mp_mesh()
    if mesh is None or p is None:
        return p
    try:
        p._data = jax.device_put(p._data, NamedSharding(mesh, P(*spec)))
    except ValueError:
        pass  # axis size may not divide on tiny test shapes
    return p


class VocabParallelEmbedding(Layer):
    """Vocab dim sharded over mp. reference: mp_layers.py:47."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num = num_embeddings
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.XavierNormal())
        _shard_param(self.weight, ("mp", None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return _constrain(out, (None, None, None))


class ColumnParallelLinear(Layer):
    """Weight (in, out) sharded on out over mp. reference: mp_layers.py:334."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr)
        self.bias = (self.create_parameter((out_features,), is_bias=True)
                     if has_bias else None)
        _shard_param(self.weight, (None, "mp"))
        if self.bias is not None:
            _shard_param(self.bias, ("mp",))

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return _constrain(out, (None,))  # replicated (all-gather by GSPMD)
        # keep sharded on the feature (last) dim
        ndim = out.ndim
        spec = [None] * (ndim - 1) + ["mp"]
        return _constrain(out, tuple(spec))


class RowParallelLinear(Layer):
    """Weight (in, out) sharded on in over mp; partial-sum output reduced by
    GSPMD. reference: mp_layers.py:541."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr)
        self.bias = (self.create_parameter((out_features,), is_bias=True)
                     if has_bias else None)
        _shard_param(self.weight, ("mp", None))

    def forward(self, x):
        if not self.input_is_parallel:
            ndim = x.ndim
            spec = [None] * (ndim - 1) + ["mp"]
            x = _constrain(x, tuple(spec))
        out = F.linear(x, self.weight, None)
        out = _constrain(out, (None,))  # forces the psum of partials
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(Layer):
    """reference: mp_layers.py:742 + c_softmax_with_cross_entropy kernel —
    GSPMD shards the softmax over the vocab-sharded logits automatically."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


class TensorParallel(Layer):
    """reference: fleet/meta_parallel/tensor_parallel.py — broadcast of
    non-TP params is unnecessary under a single controller (state is global);
    wrapper kept for API parity."""

    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


class ShardingParallel(TensorParallel):
    pass


class SegmentParallel(TensorParallel):
    """SEP axis wrapper (sequence split across ranks).
    reference: fleet/meta_parallel/segment_parallel.py:26. Sequence-dim
    activations are sharded over 'sep'; ring attention
    (paddle_tpu.ops.ring_attention) computes full attention across shards."""
    pass
