"""Fleet facade — hybrid parallel over a single jax Mesh.

reference: python/paddle/distributed/fleet/ — fleet.py:218 init,
:674 _init_hybrid_parallel_env, model.py:32 distributed_model,
base/topology.py:189 HybridCommunicateGroup (axis order pp→mp→sep→sharding→dp,
topology.py:301), base/distributed_strategy.py.

TPU-native: the rank grid IS a jax.sharding.Mesh with named axes
("pp","mp","sep","sharding","dp"); each communicator group is a mesh axis;
collectives ride ICI via GSPMD/shard_map instead of per-group NCCL
communicators.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from ...framework.core import Tensor

__all__ = ["init", "DistributedStrategy", "HybridCommunicateGroup",
           "get_hybrid_communicate_group", "distributed_model",
           "distributed_optimizer", "fleet", "worker_num", "worker_index",
           "is_first_worker", "CommunicateTopology"]

from . import meta_parallel  # noqa: F401
from . import meta_optimizers  # noqa: F401
from .utils import recompute  # noqa: F401


class DistributedStrategy:
    """reference: fleet/base/distributed_strategy.py (proto-backed)."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sep_degree": 1, "sharding_degree": 1,
            "order": ["pp", "mp", "sep", "sharding", "dp"],
        }
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.find_unused_parameters = False

    def __setattr__(self, k, v):
        object.__setattr__(self, k, v)


class CommunicateTopology:
    """reference: fleet/base/topology.py:CommunicateTopology."""

    def __init__(self, hybrid_group_names, dims):
        self._names = list(hybrid_group_names)
        self._dims = list(dims)
        self._world_size = int(np.prod(dims))
        self._rank_grid = np.arange(self._world_size).reshape(dims)

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, name):
        return self._dims[self._names.index(name)]

    def world_size(self):
        return self._world_size

    def get_rank(self, **kwargs):
        idx = tuple(kwargs[n] for n in self._names)
        return int(self._rank_grid[idx])

    def get_coord(self, rank):
        return np.unravel_index(rank, self._dims)

    def get_axis_list(self, axis_name, index):
        ax = self._names.index(axis_name)
        return np.take(self._rank_grid, index, axis=ax).reshape(-1).tolist()

    def get_comm_list(self, axis_name):
        ax = self._names.index(axis_name)
        moved = np.moveaxis(self._rank_grid, ax, -1)
        return moved.reshape(-1, self._dims[ax]).tolist()


class HybridCommunicateGroup:
    """reference: fleet/base/topology.py:189. Builds the jax Mesh; group
    objects carry their mesh axis name so collective.py can issue
    psum/ppermute over them inside compiled regions."""

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        names = topology.get_hybrid_group_names()
        dims = [topology.get_dim(n) for n in names]
        self._dims = dict(zip(names, dims))
        n_needed = int(np.prod(dims))
        devs = np.asarray(jax.devices())
        if devs.size < n_needed:
            devs = devs[np.arange(n_needed) % devs.size]
        dev_grid = devs[:n_needed].reshape(dims)
        self._mesh = Mesh(dev_grid, tuple(names))
        self._rank = 0  # single-controller: this process drives all devices

        from ..parallel_env import new_group
        self._groups = {}
        for name in names:
            g = new_group(list(range(self._dims[name])))
            g.axis_name = name
            self._groups[name] = g

    # mesh access (TPU-native surface)
    @property
    def mesh(self):
        return self._mesh

    def get_mesh(self):
        return self._mesh

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self._rank

    def get_rank_from_stage(self, stage_id, **kwargs):
        return stage_id

    # -- degrees ------------------------------------------------------------
    def get_data_parallel_world_size(self):
        return self._dims.get("dp", 1)

    def get_model_parallel_world_size(self):
        return self._dims.get("mp", 1)

    def get_pipe_parallel_world_size(self):
        return self._dims.get("pp", 1)

    def get_sep_parallel_world_size(self):
        return self._dims.get("sep", 1)

    def get_sharding_parallel_world_size(self):
        return self._dims.get("sharding", 1)

    # -- ranks (single controller: rank 0 of each axis) ---------------------
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sep_parallel_rank(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    # -- groups -------------------------------------------------------------
    def get_data_parallel_group(self):
        return self._groups.get("dp")

    def get_model_parallel_group(self):
        return self._groups.get("mp")

    def get_pipe_parallel_group(self):
        return self._groups.get("pp")

    def get_sep_parallel_group(self):
        return self._groups.get("sep")

    def get_sharding_parallel_group(self):
        return self._groups.get("sharding")

    def get_check_parallel_group(self, *a):
        return self._groups.get("mp")

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    def get_p2p_groups(self):
        return None


_hcg = None
_strategy = None


def get_hybrid_communicate_group():
    return _hcg


class _Fleet:
    _role_maker = None
    _ps_engine = None

    def init(self, role_maker=None, is_collective=False, strategy=None,
             log_level="INFO"):
        global _hcg, _strategy
        if role_maker is None and not is_collective:
            # reference entrypoint `fleet.init(is_collective=False)`:
            # PS mode with the env-derived role maker
            role_maker = PaddleCloudRoleMaker(is_collective=False)
        self._role_maker = role_maker
        if role_maker is not None and not getattr(role_maker, "_collective",
                                                  True):
            # PS mode: no device mesh to build — the sparse runtime is
            # host-side (distributed/ps); dense training stays GSPMD and
            # is initialized by the trainer when it first touches jax
            _strategy = strategy or DistributedStrategy()
            return self
        from ..parallel_env import init_parallel_env
        init_parallel_env()
        _strategy = strategy or DistributedStrategy()
        cfg = _strategy.hybrid_configs
        order = cfg.get("order", ["pp", "mp", "sep", "sharding", "dp"])
        name_map = {"pp": "pp_degree", "mp": "mp_degree", "dp": "dp_degree",
                    "sep": "sep_degree", "sharding": "sharding_degree"}
        dims = [max(int(cfg.get(name_map[n], 1) or 1), 1) for n in order]
        topo = CommunicateTopology(order, dims)
        _hcg = HybridCommunicateGroup(topo)
        return self

    @property
    def worker_num(self):
        import jax
        return jax.process_count()

    def worker_index(self):
        import jax
        return jax.process_index()

    def is_first_worker(self):
        return self.worker_index() == 0

    def barrier_worker(self):
        from ..parallel_env import barrier
        barrier()

    def distributed_model(self, model):
        """reference: fleet/model.py:32 — wrap by topology."""
        global _hcg
        if _hcg is None:
            self.init(is_collective=True)
        from .meta_parallel import (PipelineParallel, TensorParallel,
                                    ShardingParallel)
        from .meta_parallel.pp_layers import PipelineLayer
        if _hcg.get_pipe_parallel_world_size() > 1 and isinstance(model, PipelineLayer):
            return PipelineParallel(model, _hcg, _strategy)
        if _hcg.get_model_parallel_world_size() > 1:
            return TensorParallel(model, _hcg, _strategy)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        """reference: fleet/fleet.py:1427."""
        from .meta_optimizers import HybridParallelOptimizer
        global _hcg
        if _hcg is None:
            self.init(is_collective=True)
        return HybridParallelOptimizer(optimizer, _hcg, _strategy)

    def get_hybrid_communicate_group(self):
        return _hcg

    # --- PS mode lifecycle (reference: fleet.py init_server/run_server/
    # init_worker/stop_worker; runtime = distributed/ps TheOnePs) ---------
    def ps_tables(self, *table_configs):
        """Declare the sparse tables for PS mode (the reference derives
        them from the program; here they are explicit TableConfigs)."""
        from ..ps import the_one_ps
        self._ps_engine = the_one_ps.from_env(list(table_configs))
        return self._ps_engine

    def init_server(self, dirname=None, **kwargs):
        if self._ps_engine is None:
            raise RuntimeError("fleet.init_server: declare tables first "
                               "via fleet.ps_tables(*TableConfigs)")
        eng = self._ps_engine
        if eng.num_servers <= 1 or self._role_maker is None:
            eng.start_local()
        else:
            sid = self._role_maker.worker_index() \
                if self._role_maker.is_server() else 0
            eng.start_server(sid)
        if dirname:
            eng.load(dirname)
        return eng

    def run_server(self):
        if self._ps_engine is None:
            raise RuntimeError("fleet.run_server before init_server")
        self._ps_engine.run_server()

    def init_worker(self, scopes=None):
        eng = self._ps_engine
        if eng is None:
            raise RuntimeError("fleet.init_worker: declare tables first "
                               "via fleet.ps_tables(*TableConfigs)")
        if eng.client is None:
            if eng.num_servers <= 1:
                if eng.servers:  # a server started in-process: route to it
                    from ..ps.service import LocalChannel, PsClient
                    eng.client = PsClient([LocalChannel(eng.servers[0])])
                else:
                    eng.start_local()
            else:
                from ..ps.the_one_ps import server_name
                eng.connect([server_name(i)
                             for i in range(eng.num_servers)])
        return eng.client

    def stop_worker(self):
        if self._ps_engine is not None:
            self._ps_engine.stop()

    def save_persistables(self, executor=None, dirname=None, main_program=None,
                          mode=0):
        if self._ps_engine is not None and dirname:
            self._ps_engine.save(dirname)


fleet = _Fleet()
Fleet = _Fleet  # reference exports the class too
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
worker_num = lambda: fleet.worker_num
worker_index = fleet.worker_index
is_first_worker = fleet.is_first_worker
from . import elastic  # noqa: F401


# ---------------------------------------------------------------------------
# reference-surface: Fleet class, role makers, util (fleet/__init__.py)
# ---------------------------------------------------------------------------


class Role:
    """reference: fleet/base/role_maker.py Role enum."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class UtilBase:
    """reference: fleet/utils/fleet_util.py UtilBase — cross-worker helpers
    on the single-controller runtime."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        return input  # world of the controller is 1

    def barrier(self, comm_world="worker"):
        return None

    def all_gather(self, input, comm_world="worker"):
        return [input]

    def get_file_shard(self, files):
        return list(files)

    def print_on_rank(self, message, rank_id=0):
        import jax
        if jax.process_index() == rank_id:
            print(message)


class PaddleCloudRoleMaker:
    """reference: fleet/base/role_maker.py PaddleCloudRoleMaker — reads the
    cluster layout from env. PS mode (is_collective=False) reads the
    reference's TRAINING_ROLE / PADDLE_PSERVERS_IP_PORT_LIST layout; the
    server runtime itself is paddle_tpu.distributed.ps (TheOnePs)."""

    def __init__(self, is_collective=True, **kwargs):
        import os
        self._collective = bool(is_collective)
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._role = Role.WORKER
        if not self._collective:
            training_role = os.environ.get("TRAINING_ROLE", "TRAINER")
            if training_role.upper() in ("PSERVER", "SERVER"):
                self._role = Role.SERVER
                self._rank = int(os.environ.get("PADDLE_PSERVER_ID",
                                                os.environ.get(
                                                    "PADDLE_TRAINER_ID",
                                                    "0")))
            eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
            self._server_endpoints = [e for e in eps.split(",") if e]
        else:
            self._server_endpoints = []

    def worker_index(self):
        return self._rank

    def worker_num(self):
        return self._size

    def server_num(self):
        return len(self._server_endpoints) or 1

    def get_pserver_endpoints(self):
        return list(self._server_endpoints)

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def role(self):
        return self._role


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=True, current_id=0, worker_num=1,
                 role=Role.WORKER, server_endpoints=None, **kwargs):
        self._collective = bool(is_collective)
        self._rank = current_id
        self._size = worker_num
        self._role = role
        self._server_endpoints = list(server_endpoints or [])

    def role(self):
        return self._role

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER


# MultiSlot data generators — real since r5 (distributed/dataset.py):
# the pipe_command protocol feeding InMemoryDataset/QueueDataset
from ..dataset import (MultiSlotDataGenerator,  # noqa: E402,F401
                       MultiSlotStringDataGenerator)
