"""Hybrid-parallel + sharding optimizers.

reference: python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
— HybridParallelOptimizer:266, DygraphShardingOptimizer:53 (+V2:585).

TPU-native ZeRO: optimizer state arrays get a NamedSharding over the dp (or
'sharding') mesh axis — stage 1 shards optimizer states, stage 2 also
reshards grads (psum_scatter under jit), stage 3 shards params. On a single
controller this is a device_put of the state pytree; XLA handles the
gather-on-use.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["HybridParallelOptimizer", "DygraphShardingOptimizer",
           "ShardingOptimizerStage1"]


class HybridParallelOptimizer:
    """reference: hybrid_parallel_optimizer.py:266 — wraps the inner
    optimizer; grad clip already sees global (unsharded) grads under the
    single-controller model, so the cross-group norm reduction is implicit."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=False):
        self._inner_opt.clear_grad(set_to_zero)

    def minimize(self, loss, **kw):
        return self._inner_opt.minimize(loss)


def _shard_axis_sharding(hcg, arr):
    if hcg is None:
        return None
    mesh = hcg.mesh
    axis = "sharding" if hcg.get_sharding_parallel_world_size() > 1 else "dp"
    if axis not in mesh.axis_names:
        return None
    n = mesh.shape[axis]
    if arr.ndim == 0 or arr.shape[0] % n != 0:
        return None
    spec = [None] * arr.ndim
    spec[0] = axis
    return NamedSharding(mesh, P(*spec))


class DygraphShardingOptimizer:
    """ZeRO-1 (+stage knobs). reference: dygraph_sharding_optimizer.py:53."""

    def __init__(self, optimizer, hcg=None, stage=1):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._stage = stage

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        """Eager-mode fallback: runs the inner step, then re-lays-out the
        optimizer state over the sharding axis. This bounds steady-state
        memory but NOT peak memory (the full state materializes first) —
        for the real in-step ZeRO partition use
        parallel.SpmdTrainer(sharding_stage=1/2/3), which applies the
        partition via in/out_shardings inside the jitted update."""
        self._inner_opt.step()
        hcg = self._hcg
        if hcg is None:
            from . import get_hybrid_communicate_group
            hcg = get_hybrid_communicate_group()
        if hcg is None:
            return
        for pid, st in self._inner_opt._accumulators.items():
            for k, v in st.items():
                if isinstance(v, jax.Array):
                    sh = _shard_axis_sharding(hcg, v)
                    if sh is not None:
                        try:
                            st[k] = jax.device_put(v, sh)
                        except ValueError as e:
                            import warnings
                            warnings.warn(
                                f"ZeRO resharding of optimizer state "
                                f"{pid}/{k} failed ({e}); state stays "
                                f"replicated", RuntimeWarning)

    def clear_grad(self, set_to_zero=False):
        self._inner_opt.clear_grad(set_to_zero)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()


class ShardingOptimizerStage1(DygraphShardingOptimizer):
    def __init__(self, optimizer, stage=1, group=None):
        super().__init__(optimizer, None, stage)
