"""Megatron-style sequence parallelism over the 'mp' mesh axis.

reference: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py —
ScatterOp:85 / GatherOp / AllGatherOp / ReduceScatterOp PyLayers,
ColumnSequenceParallelLinear:427, RowSequenceParallelLinear, and the
allreduce hooks for SP params (:192).

TPU-native design: the reference hand-writes the collective pair
(all-gather activations before the column linear, reduce-scatter after the
row linear) as PyLayers with explicit NCCL calls. Here each "op" is a
sharding constraint on the sequence dim over the 'mp' axis; GSPMD lowers
the replicated→sharded transition to a slice/scatter, sharded→replicated
to an all-gather, and partial→sharded to a reduce-scatter — the identical
Megatron-SP communication pattern, placed by the compiler onto ICI. The
backward collectives (all-gather ↔ reduce-scatter duality) come from XLA's
transpose of the sharding constraints, so no custom VJPs are needed.

Layout convention matches the reference: activations are [s, b, h] and the
sequence dim is axis 0 (`ScatterOp` splits axis 0 unless told otherwise).
"""

from __future__ import annotations

from ....nn import functional as F
from ....nn.layer.layers import Layer
# one copy of the trace-gated sharding-constraint machinery (identity in
# eager single-controller mode, with_sharding_constraint under jit)
from ..meta_parallel.parallel_layers import _constrain, _shard_param

__all__ = [
    "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
    "scatter", "all_gather", "reduce_scatter",
    "mark_as_sequence_parallel_parameter",
    "is_sequence_parallel_parameter",
    "register_sequence_parallel_allreduce_hooks",
    "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
]

_SP_AXIS = "mp"  # Megatron SP reuses the tensor-parallel group


def _seq_spec(ndim, axis, shard):
    spec = [None] * ndim
    if shard:
        spec[axis] = _SP_AXIS
    return tuple(spec)


def scatter(x, axis=0):
    """Replicated -> sequence-sharded over mp (reference ScatterOp.forward:
    a split; backward is the all-gather, supplied by XLA's transpose)."""
    return _constrain(x, _seq_spec(x.ndim, axis, True))


def all_gather(x, axis=0):
    """Sequence-sharded -> replicated (reference GatherOp/AllGatherOp;
    backward reduce-scatters)."""
    return _constrain(x, _seq_spec(x.ndim, axis, False))


def reduce_scatter(x, axis=0):
    """Partial-sum -> sequence-sharded (reference ReduceScatterOp; GSPMD
    fuses the pending psum with the seq-dim shard into a reduce-scatter)."""
    return _constrain(x, _seq_spec(x.ndim, axis, True))


class _OpNamespace:
    """The reference exposes these as PyLayers with .apply; keep that
    spelling (`ScatterOp.apply(x)`) alongside the plain call."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, axis=0):
        return self._fn(x, axis)

    def apply(self, x, axis=0):
        return self._fn(x, axis)


ScatterOp = _OpNamespace(scatter)
GatherOp = _OpNamespace(all_gather)
AllGatherOp = _OpNamespace(all_gather)
ReduceScatterOp = _OpNamespace(reduce_scatter)


def mark_as_sequence_parallel_parameter(parameter):
    """reference: sequence_parallel_utils.py:176. Params of layers that
    consume seq-sharded activations (layernorm/bias between the row and
    column linears) need their grads summed over mp in the reference; under
    GSPMD the grad psum is inserted by the partitioner, so the mark is
    metadata only — kept for checkpoint/porting parity."""
    parameter.__dict__["sequence_parallel"] = True
    return parameter


def is_sequence_parallel_parameter(parameter):
    return bool(parameter.__dict__.get("sequence_parallel", False))


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_allreduce=False):
    """reference: sequence_parallel_utils.py:192. No-op under GSPMD (the
    compiler already reduces SP-param grads over mp); validates the marks
    so ported code fails loudly if it never marked anything."""
    marked = [p for p in model.parameters()
              if is_sequence_parallel_parameter(p)]
    if not marked:
        import warnings
        warnings.warn(
            "register_sequence_parallel_allreduce_hooks: no parameter is "
            "marked with mark_as_sequence_parallel_parameter — in the "
            "reference this means SP-param grads would silently miss their "
            "mp allreduce; mark layernorm/bias params between the row and "
            "column linears", RuntimeWarning, stacklevel=2)
    return marked


class ColumnSequenceParallelLinear(Layer):
    """Column-parallel linear whose input arrives sequence-sharded.

    reference: sequence_parallel_utils.py:427. Forward: all-gather the
    sequence dim (axis 0 of [s, b, h]) over mp, matmul with the
    output-sharded weight, keep the output feature-sharded
    (gather_output=False is the only mode, as in the reference).
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        if gather_output:
            raise ValueError(
                "ColumnSequenceParallelLinear gathers the sequence dim, not "
                "the output dim; gather_output must be False "
                "(reference sequence_parallel_utils.py:459)")
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr)
        self.bias = (self.create_parameter((out_features,), is_bias=True)
                     if has_bias else None)
        _shard_param(self.weight, (None, _SP_AXIS))
        if self.bias is not None:
            _shard_param(self.bias, (_SP_AXIS,))

    def forward(self, x):
        x = all_gather(x, axis=0)                    # [s/mp,b,h] -> [s,b,h]
        out = F.linear(x, self.weight, self.bias)
        # feature (last dim) stays sharded on mp, like the reference
        return _constrain(out, _seq_spec(out.ndim, out.ndim - 1, True))


class RowSequenceParallelLinear(Layer):
    """Row-parallel linear whose output leaves sequence-sharded.

    reference: sequence_parallel_utils.py (RowSequenceParallelLinear).
    Forward: matmul with the input-sharded weight (input arrives
    feature-sharded from the column linear), then reduce-scatter the
    partial sums over the sequence dim — output is [s/mp, b, h].
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr)
        self.bias = (self.create_parameter((out_features,), is_bias=True)
                     if has_bias else None)
        _shard_param(self.weight, (_SP_AXIS, None))

    def forward(self, x):
        if not self.input_is_parallel:
            x = _constrain(x, _seq_spec(x.ndim, x.ndim - 1, True))
        out = F.linear(x, self.weight, None)
        out = reduce_scatter(out, axis=0)            # [s,b,h] -> [s/mp,b,h]
        if self.bias is not None:
            out = out + self.bias
        return out
