"""fleet.utils — recompute (activation checkpointing).

reference: python/paddle/distributed/fleet/recompute/recompute.py:455 +
recompute_hybrid.py (TP-aware RNG).

TPU-native: recompute maps to jax.checkpoint (remat) around the block. In
eager tape mode we record one vjp over the remat-wrapped function, so the
backward re-runs the forward — the exact semantics of RecomputeFunction —
while under jit.to_static the same jax.checkpoint drives XLA rematerialization.
"""

from __future__ import annotations

import jax

from ....framework.core import Tensor, execute

__all__ = ["recompute", "recompute_sequential"]


def recompute(function, *args, **kwargs):
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)

    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    tensor_set = set(tensor_idx)
    others = list(args)

    from ....framework.random import get_rng_state, set_rng_state
    rng_snapshot = get_rng_state() if preserve_rng_state else None

    def pure(*arrays):
        it = iter(arrays)
        call_args = [Tensor(next(it), stop_gradient=args[i].stop_gradient)
                     if i in tensor_set else others[i]
                     for i in range(len(args))]
        if rng_snapshot is not None:
            set_rng_state(rng_snapshot)
        from ....framework import core as _core
        ctx = _core.TraceContext()  # suppress per-op taping inside
        with ctx:
            out = function(*call_args, **kwargs)
        if isinstance(out, Tensor):
            return out._data
        return tuple(o._data if isinstance(o, Tensor) else o for o in out)

    remat_fn = jax.checkpoint(pure)
    tensor_args = [args[i] for i in tensor_idx]
    return execute(remat_fn, *tensor_args, _name="recompute")


def recompute_sequential(ctx, functions, *args, **kwargs):
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    funcs = list(functions)
    n = len(funcs)
    seg = max(n // max(segments, 1), 1)
    out = args[0] if len(args) == 1 else args

    def run_segment(fs):
        def seg_fn(x):
            for f in fs:
                x = f(x)
            return x
        return seg_fn

    for i in range(0, n, seg):
        out = recompute(run_segment(funcs[i:i + seg]), out)
    return out


from . import sequence_parallel_utils  # noqa: E402,F401
