"""Elastic membership manager. reference:
python/paddle/distributed/fleet/elastic/manager.py:125 ElasticManager —
etcd node registry with leases + heartbeats (:218-260), membership watch
(:248), restart-on-change (elastic/collective.py); launcher flags
--nnodes N:M, --max_restart (launch/main.py:38-97).

TPU-native: the registry rides the native TCPStore (native/tcp_store.cc)
instead of etcd — same lease/heartbeat/watch semantics. On TPU pods the
actual node replacement is done by the platform (GKE/TPU VM autoscaler);
this manager detects membership change, decides GOOD/INCOMPLETE/RESTART,
and triggers the local restart callback so training resumes from the last
checkpoint.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ...resilience.faults import fault_point
from ...resilience.retry import RetryPolicy

__all__ = ["ElasticStatus", "ElasticManager"]


def _count(name):
    """Recovery telemetry (observability catalog); never fails the
    recovery path over a metrics problem."""
    try:
        from ...observability.catalog import metric
        metric(name).inc()
    except Exception:  # noqa: BLE001
        pass


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """
    em = ElasticManager(store, node_id="host0", np_range=(2, 4),
                        heartbeat_interval=5, on_change=restart_fn)
    em.register()          # announce this node
    em.start()             # heartbeats + membership watch
    status = em.watch()    # blocks until change / completion
    """

    PREFIX = "__elastic/nodes/"

    def __init__(self, store, node_id=None, np_range=(1, 1),
                 heartbeat_interval=5.0, lease_ttl=None, on_change=None,
                 max_restart=3, retry_policy=None):
        self._store = store
        self.node_id = node_id or f"{os.uname().nodename}-{os.getpid()}"
        lo, hi = (np_range if isinstance(np_range, tuple)
                  else (np_range, np_range))
        self.np_lo, self.np_hi = int(lo), int(hi)
        self._hb_interval = float(heartbeat_interval)
        self._ttl = float(lease_ttl or heartbeat_interval * 3)
        self._on_change = on_change
        self.max_restart = max_restart
        self.restarts = 0
        self._stop = threading.Event()
        self._hb_thread = None
        self._registered = False
        # transient store faults recover inside the lease budget: total
        # retry time must stay well under the ttl so a surviving node's
        # lease never expires while the store blips
        self._retry = retry_policy or RetryPolicy(
            max_attempts=3, base_delay=min(0.05, self._hb_interval / 10),
            max_delay=self._hb_interval / 2, seed=0)
        self._retry_lock = threading.Lock()

    def _store_call(self, fn, *args, op, recovery_metric):
        """Retried store op shared by the heartbeat and membership-watch
        paths. Returns (ok, value); a recovery (success after >=1 retry)
        is counted so silent flakiness shows up in the catalog."""
        with self._retry_lock:
            out = self._retry.call(fn, *args, op=op)
            if self._retry.last_retries:
                _count(recovery_metric)
            return out

    # -- registry ------------------------------------------------------------
    def _key(self, node_id=None):
        return f"{self.PREFIX}{node_id or self.node_id}"

    def register(self):
        # race-free membership index: store.add atomically allocates a slot,
        # then the node id is written under that slot — concurrent registers
        # can never clobber each other (the read-modify-write of a shared
        # list would)
        slot = self._store.add("__elastic/nslots", 1)
        self._store.set(f"__elastic/slot/{slot}", self.node_id.encode())
        self._beat()
        self._registered = True

    def deregister(self):
        if self._registered:
            self.stop()  # the heartbeat thread must die BEFORE the tombstone
            self._store.set(self._key(), b"")  # tombstone: empty lease
            self._registered = False

    def _beat(self):
        fault_point("elastic.heartbeat", node=self.node_id)
        lease = json.dumps({"t": time.time(), "pid": os.getpid()}).encode()
        self._store.set(self._key(), lease)

    def _load_index(self):
        try:
            n = int(self._store_call(
                self._store.add, "__elastic/nslots", 0,
                op="elastic.watch", recovery_metric=
                "elastic_watch_recoveries_total"))
        except Exception:  # noqa: BLE001 — store down past the retry
            return []      # budget: treat as empty, next poll retries
        seen, members = set(), []
        for slot in range(1, n + 1):
            key = f"__elastic/slot/{slot}"
            try:
                # check() first: get() blocks up to the store timeout on a
                # missing key (e.g. a node died between slot allocation and
                # the slot write), which would freeze every membership poll
                if not self._store_call(
                        self._store.check, key, op="elastic.watch",
                        recovery_metric="elastic_watch_recoveries_total"):
                    continue
                nid = self._store_call(
                    self._store.get, key, op="elastic.watch",
                    recovery_metric="elastic_watch_recoveries_total"
                ).decode()
            except Exception:  # noqa: BLE001
                continue
            if nid and nid not in seen:
                seen.add(nid)
                members.append(nid)
        return members

    def alive_nodes(self):
        """Nodes whose lease is fresh (within ttl)."""
        now = time.time()
        alive = []
        for nid in self._load_index():
            try:
                if not self._store_call(
                        self._store.check, self._key(nid),
                        op="elastic.watch", recovery_metric=
                        "elastic_watch_recoveries_total"):
                    continue
                raw = self._store_call(
                    self._store.get, self._key(nid), op="elastic.watch",
                    recovery_metric="elastic_watch_recoveries_total")
            except Exception:  # noqa: BLE001
                continue
            if not raw:
                continue  # tombstone
            try:
                lease = json.loads(raw.decode())
            except ValueError:
                continue
            if now - lease["t"] <= self._ttl:
                alive.append(nid)
        return alive

    # -- heartbeat loop ------------------------------------------------------
    def start(self):
        if self._hb_thread is None:
            self._stop.clear()
            self._hb_thread = threading.Thread(target=self._hb_loop,
                                               daemon=True, name="elastic-hb")
            self._hb_thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=self._hb_interval * 2)
            self._hb_thread = None

    def _hb_loop(self):
        while not self._stop.wait(self._hb_interval):
            try:
                self._store_call(
                    self._beat, op="elastic.heartbeat",
                    recovery_metric="elastic_heartbeat_recoveries_total")
            except Exception:  # noqa: BLE001 — store down past the retry
                # budget: keep beating, the lease may survive — counted,
                # never raised into the owning replica's serving loop
                _count("elastic_beat_failures_total")

    # -- membership decisions ------------------------------------------------
    def health(self):
        n = len(self.alive_nodes())
        if n < self.np_lo:
            return ElasticStatus.HOLD       # not enough nodes to run
        if n > self.np_hi:
            return ElasticStatus.ERROR      # over-subscribed (config bug)
        return ElasticStatus.COMPLETED

    def watch(self, poll=None, max_wait=None):
        """Block until membership changes from the current set (or timeout).
        Returns RESTART on change (train must re-init the mesh), HOLD if
        below np_lo, EXIT when max_restart exhausted."""
        poll = poll or self._hb_interval
        baseline = set(self.alive_nodes())
        deadline = time.time() + max_wait if max_wait else None
        while not self._stop.is_set():
            time.sleep(poll)
            cur = set(self.alive_nodes())
            if cur != baseline:
                _count("elastic_membership_changes_total")
                if len(cur) < self.np_lo:
                    return ElasticStatus.HOLD
                self.restarts += 1
                if self.restarts > self.max_restart:
                    return ElasticStatus.EXIT
                if self._on_change is not None:
                    self._on_change(sorted(cur))
                _count("elastic_restarts_total")
                return ElasticStatus.RESTART
            if deadline and time.time() > deadline:
                return ElasticStatus.COMPLETED
        return ElasticStatus.COMPLETED
