"""DataParallel wrapper.

reference: python/paddle/parallel.py DataParallel + C++ EagerReducer
(paddle/fluid/distributed/collective/reducer.cc — bucketed allreduce).

TPU-native: DP is a sharding, not a wrapper protocol. Inputs sharded on the
batch axis + replicated params under jit make XLA insert the gradient
all-reduce (bucketing/overlap is the XLA latency-hiding scheduler's job).
This class keeps API parity (no_sync, scale_loss) and applies batch-axis
sharding when a mesh is present.
"""

from __future__ import annotations

import contextlib

from ..nn.layer.layers import Layer

__all__ = ["DataParallel"]


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        yield

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, state_dict, *a, **k):
        return self._layers.set_state_dict(state_dict, *a, **k)
