"""Semi-auto parallel (DTensor-style) API.

reference: python/paddle/distributed/auto_parallel/api.py —
shard_tensor:205, reshard:727, shard_layer:828, shard_optimizer:1613,
dtensor_from_local:641, unshard_dtensor:2876, shard_dataloader:3230.

TPU-native: a "DistTensor" is just a Tensor whose jax.Array carries a
NamedSharding; SPMD propagation (the reference's 113 C++ spmd rules) is
GSPMD's job inside jit. Partial placements materialize via psum on reshard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.core import Parameter, Tensor, execute
from .placement import (Partial, ProcessMesh, Replicate, Shard,
                        named_sharding, to_partition_spec)

__all__ = ["shard_tensor", "dtensor_from_fn", "reshard", "shard_layer",
           "shard_optimizer", "unshard_dtensor", "dtensor_from_local",
           "shard_dataloader", "to_distributed"]


def _attach_dist(t, mesh, placements):
    t.process_mesh = mesh
    t.placements = list(placements)
    return t


def shard_tensor(data, mesh=None, placements=None, dtype=None, place=None,
                 stop_gradient=None, dist_attr=None):
    """reference: auto_parallel/api.py:205. Accepts either the placements
    flavor (mesh, [Shard/Replicate/Partial...]) or the legacy DistAttr
    flavor (mesh + per-tensor-axis sharding_specs)."""
    legacy = dist_attr if dist_attr is not None else (
        mesh if hasattr(mesh, "sharding_specs") else None)
    if legacy is not None:
        from .placement import Shard, Replicate
        mesh = legacy.process_mesh
        dim_names = list(getattr(mesh, "dim_names", []))
        placements = [Replicate() for _ in dim_names]
        for axis, spec in enumerate(legacy.sharding_specs):
            if spec is not None:
                placements[dim_names.index(spec)] = Shard(axis)
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    sharding = named_sharding(mesh, placements, t._data.ndim)
    arr = jax.device_put(t._data, sharding)
    # Partial: value is conceptually unreduced; materialize by dividing the
    # replicated value (paddle init use-case: fresh partial grads are zeros)
    if isinstance(t, Parameter):
        out = t
        out._data = arr
    else:
        out = Tensor(arr, stop_gradient=t.stop_gradient
                     if stop_gradient is None else stop_gradient)
    if stop_gradient is not None:
        out.stop_gradient = stop_gradient
    return _attach_dist(out, mesh, placements)


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def dtensor_from_local(local_tensor, mesh, placements):
    """reference: auto_parallel/api.py:641. Single-controller: local shards
    assemble via jax.make_array_from_single_device_arrays when multi-process;
    single-process path treats the local tensor as the global value."""
    return shard_tensor(local_tensor, mesh, placements)


def reshard(dist_tensor, mesh, placements):
    """reference: auto_parallel/api.py:727 + the C++ reshard rule library
    (paddle/phi/core/distributed/auto_parallel/reshard/*) — here one
    device_put: XLA derives the minimal collective (all-gather for s→r,
    slice for r→s, all-to-all for s→s').

    Partial (p→r/p→s) needs no eager collective in this architecture:
    DistTensors are global-view (same as the reference's DistTensor — its
    materialized value is the reduced sum), and the single controller holds
    exactly that reduced global array, so dropping the Partial mark IS the
    p→r materialization. Inside jit, unreduced partial states only arise
    between ops, where GSPMD inserts the psum/reduce-scatter — the role of
    the reference's p_to_r/p_to_s rules (see
    tests/test_auto_parallel.py::TestPartialPlacement for the compiled
    row-parallel case)."""
    sharding = named_sharding(mesh, placements, dist_tensor._data.ndim)

    def f(a):
        return jax.lax.with_sharding_constraint(a, sharding) \
            if _in_trace() else jax.device_put(a, sharding)

    out = execute(f, dist_tensor, _name="reshard")
    return _attach_dist(out, mesh, placements)


def _in_trace():
    from ..framework import core as _core
    return _core.in_trace()


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """reference: auto_parallel/api.py:828 — apply shard_fn(name, layer, mesh)
    to every sublayer; default replicates parameters over the mesh."""

    def default_shard_fn(name, sublayer, mesh):
        for pname, p in sublayer._parameters.items():
            if p is not None:
                shard_tensor(p, mesh, [Replicate() for _ in range(mesh.ndim)])

    fn = shard_fn or default_shard_fn
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


class _ShardOptimizer:
    """reference: auto_parallel/api.py:1003. Wraps an optimizer so state
    tensors inherit / shard like their parameters (ZeRO via shard_fn)."""

    def __init__(self, optimizer, shard_fn=None):
        self._inner = optimizer
        self._shard_fn = shard_fn

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        self._inner.step()
        if self._shard_fn is not None:
            for p in self._inner._parameter_list:
                st = self._inner._accumulators.get(id(p))
                if st:
                    for k, v in st.items():
                        st[k] = self._shard_fn(k, p, Tensor(v))._data \
                            if isinstance(v, jax.Array) else v

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)


def shard_optimizer(optimizer, shard_fn=None):
    return _ShardOptimizer(optimizer, shard_fn)


def unshard_dtensor(dist_tensor):
    """reference: auto_parallel/api.py:2876 — gather to replicated."""
    arr = dist_tensor._data
    mesh = getattr(dist_tensor, "process_mesh", None)
    if mesh is None:
        return dist_tensor
    sharding = named_sharding(mesh, [Replicate()] * mesh.ndim, arr.ndim)
    out = Tensor(jax.device_put(arr, sharding),
                 stop_gradient=dist_tensor.stop_gradient)
    return out


class _ShardDataLoader:
    def __init__(self, dataloader, meshes, shard_dims=None):
        self._dl = dataloader
        self._meshes = meshes if isinstance(meshes, (list, tuple)) else [meshes]
        self._shard_dims = shard_dims

    def __iter__(self):
        mesh = self._meshes[0]
        dim = self._shard_dims
        if isinstance(dim, str):
            axis = mesh.dim_names.index(dim)
        else:
            axis = dim if dim is not None else None
        for batch in self._dl:
            if axis is None:
                yield batch
                continue
            placements = [Shard(0) if i == axis else Replicate()
                          for i in range(mesh.ndim)]
            yield jax.tree_util.tree_map(
                lambda t: shard_tensor(t, mesh, placements)
                if isinstance(t, Tensor) else t,
                batch, is_leaf=lambda v: isinstance(v, Tensor))

    def __len__(self):
        return len(self._dl)


def shard_dataloader(dataloader, meshes, shard_dims=None, is_dataset_splitted=False):
    """reference: auto_parallel/api.py:3230."""
    return _ShardDataLoader(dataloader, meshes, shard_dims)


def to_distributed(model, optimizer=None, dataloader=None, device_num=None,
                   node_num=1, config=None):
    """One-call auto-parallel entry.

    reference: python/paddle/distributed/auto_parallel/high_level_api.py
    to_distributed — parallelize a model over all visible devices.

    TPU-native: build a 1-axis 'dp' ProcessMesh over the devices, lay every
    parameter out replicated on it, and shard each batch's leading dim over
    'dp'. Eager ops then run under GSPMD sharding propagation (data
    parallelism with compiler-inserted grad reduction); jit/to_static over
    the same arrays compiles the identical layout. Returns the
    (model, optimizer, dataloader) triple like the reference.
    """
    n = device_num or len(jax.devices())
    n = min(n, len(jax.devices()))
    mesh = ProcessMesh(shape=[n], dim_names=["dp"])
    replicated = [Replicate()]
    for _, p in model.named_parameters():
        shard_tensor(p, mesh, replicated)
    for name, buf in getattr(model, "named_buffers", lambda: [])():
        if isinstance(buf, Tensor):
            # shard_tensor only rebinds Parameters in place; buffers need the
            # replicated array written back explicitly
            buf._data = shard_tensor(buf, mesh, replicated)._data
            _attach_dist(buf, mesh, replicated)
    if dataloader is not None:
        dataloader = shard_dataloader(dataloader, mesh, shard_dims="dp")
    return model, optimizer, dataloader
