"""Collective communication API.

reference: python/paddle/distributed/communication/ (all_reduce.py etc.),
backed by ProcessGroupNCCL (paddle/fluid/distributed/collective/) and
collective PHI kernels (paddle/phi/kernels/gpu/all_reduce_kernel.cu...).

TPU-native: collectives are XLA ops. Inside a shard_map/pjit region they map
to jax.lax.psum / all_gather / ppermute / all_to_all over a named mesh axis
(riding ICI); eagerly on a single controller the "world" of the calling
process is size 1, so eager collectives are identity — real cross-device
reduction happens inside compiled regions, which is where all hot-path
communication belongs on TPU. Groups created by fleet carry their mesh axis
name so the same Python call sites work in both modes.
"""

from __future__ import annotations

import json

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, execute
from .parallel_env import Group, get_world_size, new_group  # noqa: F401

__all__ = ["ReduceOp", "all_reduce", "all_gather", "all_gather_object",
           "all_to_all", "all_to_all_single", "reduce_scatter", "broadcast",
           "reduce", "scatter", "gather", "send", "recv", "isend", "irecv",
           "P2POp", "batch_isend_irecv", "split", "stream"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _axis_name(group):
    return getattr(group, "axis_name", None)


def _in_shardmap(arr):
    # inside a shard_map/pjit trace arrays are tracers
    return isinstance(arr, jax.core.Tracer)


def _psum_like(arr, op, axis):
    if op in (ReduceOp.SUM, "sum"):
        return jax.lax.psum(arr, axis)
    if op in (ReduceOp.MAX, "max"):
        return jax.lax.pmax(arr, axis)
    if op in (ReduceOp.MIN, "min"):
        return jax.lax.pmin(arr, axis)
    if op in (ReduceOp.AVG, "avg"):
        return jax.lax.pmean(arr, axis)
    if op in (ReduceOp.PROD, "prod"):
        return jnp.exp(jax.lax.psum(jnp.log(arr), axis))
    raise ValueError(op)


class _Task:
    def wait(self):
        return True

    def is_completed(self):
        return True


def _eager_world(group):
    return group.nranks if group is not None else get_world_size()


def _is_multiprocess_world(group):
    """True when this is a REAL multi-process world (jax.distributed
    initialized, one controller per host) and `group` spans it — the regime
    where eager collectives communicate over the coordination backend
    (gloo on CPU, ICI/DCN on TPU pods)."""
    n = jax.process_count()
    if n <= 1:
        return False
    return group is None or set(group.ranks) == set(range(n))


def _process_allgather(arr):
    """Host-level allgather: (world, *shape) with rank r's value at [r].
    reference analog: ProcessGroup allgather over NCCL/gloo."""
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(arr)


def _require_trivial_world(group, name):
    """Eager (non-compiled) collectives are only correct when the calling
    world is size 1 — with a real multi-rank group, silently returning the
    input would compute WRONG numbers for ported multi-process code.
    reference behavior: the call would actually communicate; here in-process
    device parallelism belongs inside shard_map/jit, so we fail loudly.
    (A REAL multi-process world is handled before this guard via the
    multihost path.)"""
    n = _eager_world(group)
    if n > 1:
        raise RuntimeError(
            f"{name}: eager collective over a world of size {n} is not "
            "supported on the single-controller TPU runtime — run the op "
            "inside a compiled region (shard_map/jit over the group's mesh "
            "axis), or use parallel.SpmdTrainer which inserts collectives "
            "via GSPMD; sub-world eager groups are compiled-only even in "
            "multi-process runs")


#: one source of truth for ReduceOp dispatch: stacked-axis reducer name
#: (host-level eager path) — _psum_like above covers the shard_map path
_STACK_REDUCERS = {ReduceOp.SUM: "sum", ReduceOp.MAX: "max",
                   ReduceOp.MIN: "min", ReduceOp.AVG: "mean",
                   ReduceOp.PROD: "prod"}


def _reduce_stacked(g, op):
    """Reduce a (world, ...) stack along axis 0 by ReduceOp."""
    name = _STACK_REDUCERS.get(op) or _STACK_REDUCERS.get(
        {"sum": ReduceOp.SUM, "max": ReduceOp.MAX, "min": ReduceOp.MIN,
         "avg": ReduceOp.AVG, "prod": ReduceOp.PROD}.get(op))
    if name is None:
        raise ValueError(op)
    return getattr(g, name)(0)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis_name(group)
    if axis is not None and _in_shardmap(tensor._data):
        out = execute(lambda a: _psum_like(a, op, axis), tensor, _name="all_reduce")
        tensor._rebind(out)
        return _Task()
    if _is_multiprocess_world(group) and not _in_shardmap(tensor._data):
        red = _reduce_stacked(_process_allgather(tensor._data), op)
        tensor._rebind(Tensor(jnp.asarray(red),
                              stop_gradient=tensor.stop_gradient))
        return _Task()
    _require_trivial_world(group, "all_reduce")
    return _Task()  # world size 1: reduction over one rank is identity


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    axis = _axis_name(group)
    if axis is not None and _in_shardmap(tensor._data):
        gathered = execute(lambda a: jax.lax.all_gather(a, axis), tensor,
                           _name="all_gather")
        n = gathered.shape[0]
        from ..tensor.manipulation import unbind
        tensor_list.extend(unbind(gathered, 0))
        return _Task()
    if _is_multiprocess_world(group) and not _in_shardmap(tensor._data):
        g = _process_allgather(tensor._data)  # (world, ...)
        tensor_list.extend(Tensor(jnp.asarray(g[i]), stop_gradient=True)
                           for i in range(g.shape[0]))
        return _Task()
    _require_trivial_world(group, "all_gather")
    tensor_list.append(tensor)
    return _Task()


def all_gather_object(object_list, obj, group=None):
    _require_trivial_world(group, "all_gather_object")
    object_list.append(obj)
    return _Task()


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    axis = _axis_name(group)
    if axis is not None and _in_shardmap(in_tensor_list[0]._data):
        from ..tensor.manipulation import stack, unbind
        stacked = stack(in_tensor_list, 0)
        out = execute(
            lambda a: jax.lax.all_to_all(a, axis, split_axis=0, concat_axis=0,
                                         tiled=False),
            stacked, _name="all_to_all")
        out_tensor_list.extend(unbind(out, 0))
        return _Task()
    _require_trivial_world(group, "all_to_all")
    out_tensor_list.extend(in_tensor_list)
    return _Task()


def all_to_all_single(out_tensor, in_tensor, out_split_sizes=None,
                      in_split_sizes=None, group=None, sync_op=True):
    axis = _axis_name(group)
    if axis is not None and _in_shardmap(in_tensor._data):
        out = execute(
            lambda a: jax.lax.all_to_all(
                a.reshape((group.nranks, -1) + a.shape[1:]), axis, 0, 0,
                tiled=False).reshape(a.shape),
            in_tensor, _name="all_to_all_single")
        out_tensor._rebind(out)
        return _Task()
    _require_trivial_world(group, "all_to_all_single")
    out_tensor._rebind(in_tensor.clone())
    return _Task()


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis_name(group)
    if axis is not None and _in_shardmap(tensor_list[0]._data):
        from ..tensor.manipulation import concat
        full = concat(tensor_list, 0)
        out = execute(
            lambda a: jax.lax.psum_scatter(a, axis, scatter_dimension=0,
                                           tiled=True),
            full, _name="reduce_scatter")
        tensor._rebind(out)
        return _Task()
    if _is_multiprocess_world(group) and not _in_shardmap(tensor_list[0]._data):
        # host-level: allgather each rank's (world, ...) stack, reduce over
        # the rank axis, keep this rank's chunk — the eager gloo analog of
        # ncclReduceScatter
        stack = jnp.stack([t._data for t in tensor_list])
        red = _reduce_stacked(_process_allgather(stack), op)  # (world, ...)
        tensor._rebind(Tensor(jnp.asarray(red[jax.process_index()]),
                              stop_gradient=tensor.stop_gradient))
        return _Task()
    _require_trivial_world(group, "reduce_scatter")
    tensor._rebind(tensor_list[0])
    return _Task()


def broadcast(tensor, src=0, group=None, sync_op=True):
    if _in_shardmap(tensor._data):
        # inside a compiled region values are replicated by construction
        # (or the caller shards them explicitly); never dial the host path
        # on a tracer
        return _Task()
    if _is_multiprocess_world(group):
        # host-level broadcast: ship only src's value (no full allgather)
        from jax.experimental import multihost_utils
        out = multihost_utils.broadcast_one_to_all(
            tensor._data, is_source=jax.process_index() == src)
        tensor._rebind(Tensor(jnp.asarray(out),
                              stop_gradient=tensor.stop_gradient))
        return _Task()
    if jax.process_count() > 1:
        # sub-world eager group in a multi-process run: compiled-only
        _require_trivial_world(group, "broadcast")
        return _Task()
    # single-process: replicated-by-construction (jax arrays are global),
    # so broadcast is a true no-op
    return _Task()


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if _is_multiprocess_world(group) and not _in_shardmap(tensor._data):
        # host-level: only src's list matters; ship it to everyone and keep
        # this rank's element (broadcast_one_to_all wants equal shapes on
        # every rank, so non-src ranks contribute a zero stack)
        from jax.experimental import multihost_utils
        me = jax.process_index()
        if me == src:
            if not tensor_list:
                raise ValueError("scatter: src rank needs tensor_list")
            stack = jnp.stack([t._data for t in tensor_list])
        else:
            n = jax.process_count()
            stack = jnp.zeros((n,) + tuple(tensor._data.shape),
                              tensor._data.dtype)
        out = multihost_utils.broadcast_one_to_all(stack,
                                                   is_source=me == src)
        tensor._rebind(Tensor(jnp.asarray(out[me]),
                              stop_gradient=tensor.stop_gradient))
        return _Task()
    _require_trivial_world(group, "scatter")
    if tensor_list:
        tensor._rebind(tensor_list[0])
    return _Task()


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    if _is_multiprocess_world(group) and not _in_shardmap(tensor._data):
        g = _process_allgather(tensor._data)  # (world, ...)
        if gather_list is not None and jax.process_index() == dst:
            gather_list.extend(Tensor(jnp.asarray(g[i]), stop_gradient=True)
                               for i in range(g.shape[0]))
        return _Task()
    _require_trivial_world(group, "gather")
    if gather_list is not None:
        gather_list.append(tensor)
    return _Task()


#: per-(src, dst) sequence counters for store-backed p2p: both endpoints
#: increment their own view per call, so matching send/recv pairs agree on
#: the key without any extra round trip
_P2P_SEQ: dict = {}


def _p2p_store():
    from . import parallel_env
    store = parallel_env.get_store()
    if store is None:
        raise RuntimeError(
            "eager send/recv needs the multi-process TCPStore "
            "(init_parallel_env with a PADDLE_MASTER rendezvous)")
    return store


def send(tensor, dst=0, group=None, sync_op=True):
    """Host-level p2p over the native TCPStore (the eager gloo-send analog;
    reference: ProcessGroup::Send). Inside compiled pipeline schedules p2p
    is lax.ppermute — this path serves eager control-plane transfers."""
    if jax.process_count() <= 1:
        raise NotImplementedError(
            "eager send/recv is cross-process only; in-process pipelines "
            "use lax.ppermute (distributed.fleet.meta_parallel.pipeline)")
    store = _p2p_store()
    me = jax.process_index()
    seq = _P2P_SEQ.setdefault((me, dst), 0)
    _P2P_SEQ[(me, dst)] = seq + 1
    arr = np.asarray(tensor._data)
    header = json.dumps({"dtype": str(arr.dtype),
                         "shape": list(arr.shape)}).encode()
    store.set(f"__p2p/{me}->{dst}/{seq}",
              len(header).to_bytes(4, "big") + header + arr.tobytes())
    return _Task()


def recv(tensor, src=0, group=None, sync_op=True):
    if jax.process_count() <= 1:
        raise NotImplementedError(
            "eager send/recv is cross-process only; in-process pipelines "
            "use lax.ppermute (distributed.fleet.meta_parallel.pipeline)")
    store = _p2p_store()
    me = jax.process_index()
    seq = _P2P_SEQ.setdefault((src, me), 0)
    _P2P_SEQ[(src, me)] = seq + 1
    key = f"__p2p/{src}->{me}/{seq}"
    store.wait(key)
    raw = store.get(key)
    hlen = int.from_bytes(raw[:4], "big")
    header = json.loads(raw[4:4 + hlen].decode())
    arr = np.frombuffer(raw[4 + hlen:],
                        dtype=np.dtype(header["dtype"])).reshape(
        header["shape"])
    tensor._rebind(Tensor(jnp.asarray(arr),
                          stop_gradient=tensor.stop_gradient))
    try:
        store.delete_key(key)  # one-shot mailbox: don't grow the store
    except Exception:  # noqa: BLE001 — older store without delete
        pass
    return _Task()


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group)


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Issue a batch of P2POps (reference:
    python/paddle/distributed/communication/batch_isend_irecv.py). The
    store-backed transport is asynchronous on the send side, so posting
    all sends before any recv keeps the usual exchange patterns
    deadlock-free on two-sided schedules."""
    if jax.process_count() <= 1:
        raise NotImplementedError(
            "batched p2p is cross-process only; compiled pipeline "
            "schedules use lax.ppermute")
    tasks = []
    sends = [p for p in p2p_op_list if p.op in (send, isend)]
    recvs = [p for p in p2p_op_list if p.op in (recv, irecv)]
    if len(sends) + len(recvs) != len(p2p_op_list):
        raise ValueError("P2POp.op must be send/isend/recv/irecv")
    for p in sends:
        tasks.append(send(p.tensor, p.peer, p.group))
    for p in recvs:
        tasks.append(recv(p.tensor, p.peer, p.group))
    return tasks


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          inner_rank=-1):
    raise NotImplementedError("use fleet.meta_parallel TP layers")


# paddle.distributed.stream IS communication.stream (reference:
# python/paddle/distributed/communication/stream/) — the module carries the
# Tensor flavor (one pre-sized tensor = nranks chunks); a plain alias to
# the functions above would silently iterate a Tensor input into 0-d
# scalars. Imported at the bottom: stream.py imports this module back.
from .communication import stream  # noqa: E402,F401
