"""Distributed checkpoint: sharded save / reshard-on-load.

reference: python/paddle/distributed/checkpoint/ — save_state_dict.py:145,
load_state_dict.py, metadata.py (dedup across ranks :117, async save :46).

TPU-native: orbax-style layout — per-array files + a metadata index; on load
arrays are placed onto the current mesh/sharding (reshard-on-load). Async
save runs on a background thread (device→host copy is the only sync part),
matching the reference's background-process async save.
"""

from __future__ import annotations

import json
import os
import pickle
import threading

import numpy as np

import jax

from ...framework.core import Tensor

__all__ = ["save_state_dict", "load_state_dict"]

_async_tasks: list[threading.Thread] = []


def _wait_async():
    global _async_tasks
    for t in _async_tasks:
        t.join()
    _async_tasks = []


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    async_save=False):
    """reference: checkpoint/save_state_dict.py:145."""
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    meta = {"version": 1, "arrays": {}}
    host_arrays = {}
    for k, v in state_dict.items():
        arr = v._data if isinstance(v, Tensor) else v
        if isinstance(arr, jax.Array):
            np_arr = np.asarray(arr)  # device→host (gathers if sharded)
        else:
            np_arr = np.asarray(arr)
        host_arrays[k] = np_arr
        meta["arrays"][k] = {"shape": list(np_arr.shape),
                             "dtype": str(np_arr.dtype),
                             "file": f"rank{rank}.data"}

    def write():
        with open(os.path.join(path, f"rank{rank}.data"), "wb") as f:
            pickle.dump(host_arrays, f, protocol=4)
        if rank == coordinator_rank:
            with open(os.path.join(path, "metadata.json"), "w") as f:
                json.dump(meta, f)

    if async_save:
        t = threading.Thread(target=write, daemon=True)
        t.start()
        _async_tasks.append(t)
    else:
        write()


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, offload=False):
    """reference: checkpoint/load_state_dict.py — fills `state_dict` tensors
    in place, resharding to each tensor's current sharding."""
    _wait_async()
    rank = jax.process_index()
    fp = os.path.join(path, f"rank{rank}.data")
    if not os.path.exists(fp):
        fp = os.path.join(path, "rank0.data")
    with open(fp, "rb") as f:
        host_arrays = pickle.load(f)
    for k, v in state_dict.items():
        if k not in host_arrays:
            raise KeyError(f"checkpoint missing key {k}")
        arr = host_arrays[k]
        if isinstance(v, Tensor):
            target_sharding = getattr(v._data, "sharding", None)
            import jax.numpy as jnp
            new = jnp.asarray(arr, dtype=v._data.dtype).reshape(v._data.shape)
            if target_sharding is not None:
                new = jax.device_put(new, target_sharding)  # reshard-on-load
            v._data = new
    return state_dict
