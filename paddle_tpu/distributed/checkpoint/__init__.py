"""Distributed checkpoint: sharded save / reshard-on-load.

reference capability: python/paddle/distributed/checkpoint/ —
save_state_dict.py:145 (per-rank shard files + global metadata),
save_state_dict.py:117 (dedup of replicated tensors across ranks),
metadata.py (LocalTensorMetadata/LocalTensorIndex), load_state_dict.py
(reshard-on-load onto a different mesh/placement), async save :46.

TPU-native design: each process writes ONLY the array chunks it owns
(`arr.addressable_shards`, one replica per distinct chunk globally — the
owner is the lowest (process_index, device_id) holder, computed
deterministically on every host from the sharding, no communication).
Every chunk is its own `.npy` file (reference uses per-tensor files +
metadata, save_state_dict.py:145): loads memory-map only the chunks that
overlap the destination blocks, and nothing goes through pickle.
`metadata.json` records the global layout: per-array shape/dtype and the
chunk → file map. Load assembles each destination device's block from the
overlapping saved chunks and builds the array with
`jax.make_array_from_single_device_arrays`, so a checkpoint saved from a
(dp=8) mesh loads onto a (dp=2,mp=2) mesh — or a single chip — without any
rank reading bytes it does not need.

Durability: every file is written to a temp name then os.replace'd
(atomic), metadata goes last, and async save runs on a NON-daemon thread —
process exit joins it, so a returned save_state_dict(async_save=True) can
never leave a truncated checkpoint.

Atomicity under kill-mid-save: chunk files are VERSIONED by a save
sequence number (read from the previous metadata.json in the same dir,
so every host derives the same seq without communication). A save that
dies between chunk writes and the metadata os.replace leaves the
previous metadata pointing at the previous seq's untouched files — the
new seq's orphans are garbage-collected by the next successful save.
Integrity: every locally-owned chunk's sha256 goes into metadata.json;
load verifies each chunk the first time it is read, and a truncated or
corrupt file raises `CheckpointCorruptionError` naming the file. IO is
retried via resilience.RetryPolicy; the fault sites `ckpt.chunk_write`
and `ckpt.metadata_replace` make both failure windows drillable.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading

import numpy as np

import jax

from ...framework.core import Tensor
from ...resilience.faults import fault_point
from ...resilience.retry import RetryPolicy

__all__ = ["save_state_dict", "load_state_dict",
           "CheckpointCorruptionError"]


class CheckpointCorruptionError(RuntimeError):
    """A chunk file is missing, truncated, or fails its recorded sha256.
    Carries the offending file name — never a numpy decode traceback."""

    def __init__(self, file, reason):
        super().__init__(f"checkpoint chunk {file!r} is corrupt: {reason}")
        self.file = file
        self.reason = reason


def _count(name):
    """Checkpoint telemetry (observability catalog); the save/load path
    never fails over a metrics problem."""
    try:
        from ...observability.catalog import metric
        metric(name).inc()
    except Exception:  # noqa: BLE001
        pass

# transient-IO retry for chunk/metadata writes: short, deterministic
# backoff (writes happen inside the training step cadence)
_IO_RETRY = RetryPolicy(max_attempts=3, base_delay=0.02, max_delay=0.2,
                        seed=0)

_async_tasks: list[threading.Thread] = []


def _wait_async():
    global _async_tasks
    for t in _async_tasks:
        t.join()
    _async_tasks = []


def _unwrap(v):
    return v._data if isinstance(v, Tensor) else v


def _norm_index(index, shape):
    """Normalize a shard index (tuple of slices) to ((start, stop), ...)."""
    out = []
    for dim, sl in enumerate(index):
        start = 0 if sl.start is None else int(sl.start)
        stop = shape[dim] if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _chunk_key(bounds):
    return ";".join(f"{a}:{b}" for a, b in bounds)


def _global_chunks(arr):
    """Deterministic global chunk map for a (possibly sharded) jax.Array.

    Returns {chunk_key: {"bounds": ..., "owner_process": int,
                         "owner_device": int}} — every host computes the same
    owners from the sharding alone (analog of the reference's cross-rank
    dedup, save_state_dict.py:117, done without communication).
    """
    shape = arr.shape
    sharding = getattr(arr, "sharding", None)
    if sharding is None:
        bounds = tuple((0, s) for s in shape)
        return {_chunk_key(bounds): {"bounds": bounds, "owner_process": 0,
                                     "owner_device": -1}}
    groups = {}
    for dev, index in sharding.devices_indices_map(shape).items():
        bounds = _norm_index(index, shape)
        key = _chunk_key(bounds)
        cur = groups.get(key)
        rank = (getattr(dev, "process_index", 0), dev.id)
        if cur is None or rank < (cur["owner_process"], cur["owner_device"]):
            groups[key] = {"bounds": bounds, "owner_process": rank[0],
                           "owner_device": rank[1]}
    return groups


def _chunk_file(seq, owner_rank, key, chunk_key):
    """Deterministic per-chunk file name — every host derives the same map
    from (save seq, array name, bounds, owner) without communication. The
    seq prefix keeps concurrent-with-crash saves from overwriting the
    files the previous (complete) metadata references."""
    h = hashlib.sha1(f"{key}\x00{chunk_key}".encode()).hexdigest()[:16]
    return f"s{seq}_r{owner_rank}_{h}.npy"


def _next_save_seq(path):
    """Previous metadata's save_seq + 1 (0 for a fresh dir). All hosts
    read the same shared checkpoint dir, so all derive the same seq;
    pre-seq checkpoints (no field) behave as seq 0."""
    try:
        with open(os.path.join(path, "metadata.json")) as f:
            return int(json.load(f).get("save_seq", 0)) + 1
    except (OSError, ValueError):
        return 0


def _sha256(data):
    return hashlib.sha256(np.ascontiguousarray(data).tobytes()).hexdigest()


def _atomic_write_npy(path, fname, data):
    fault_point("ckpt.chunk_write", file=fname)
    # pid-unique tmp: redundant same-step writers (each process of a CPU
    # drill believes it is process 0 and owns the same chunks) must never
    # interleave bytes in a shared tmp file; both replaces commit
    # identical data
    tmp = os.path.join(path, f"{fname}.{os.getpid()}.tmp")
    np.save(tmp, data, allow_pickle=False)
    # np.save appends .npy to names without it
    os.replace(tmp + ".npy" if not tmp.endswith(".npy") else tmp,
               os.path.join(path, fname))


def _replace_metadata(path, meta):
    tmp = os.path.join(path, f"metadata.json.{os.getpid()}.tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    # the kill-mid-save window: chunks are on disk, the previous
    # metadata is still live until this replace commits the new save
    fault_point("ckpt.metadata_replace")
    os.replace(tmp, os.path.join(path, "metadata.json"))


_SEQ_RE = re.compile(r"^s(\d+)_")


def _gc_stale_chunks(path, meta):
    """After a committed save, drop chunk files no metadata references:
    old seqs' data and orphans of crashed saves. Files of the committed
    seq and the one before are kept even when unreferenced — a redundant
    concurrent writer (see _atomic_write_npy) one save behind may still
    commit them, and deleting under it would leave its metadata dangling.
    Best-effort — a failed unlink never fails the save."""
    live = {c["file"] for a in meta["arrays"].values() for c in a["chunks"]}
    keep_seq = int(meta.get("save_seq", 0)) - 1
    try:
        entries = os.listdir(path)
    except OSError:
        return
    for fname in entries:
        if fname in live or fname == "metadata.json":
            continue
        if fname.endswith(".npy") or fname.endswith(".tmp"):
            m = _SEQ_RE.match(fname)
            if m and int(m.group(1)) >= keep_seq:
                continue
            try:
                os.unlink(os.path.join(path, fname))
            except OSError:
                pass


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    async_save=False):
    """Sharded save: this process writes only chunks it owns, one .npy file
    per chunk, each atomically renamed into place; metadata.json last.

    reference: checkpoint/save_state_dict.py:145.
    """
    _count("checkpoint_saves_total")
    # a still-running async save must commit before its seq is read
    _wait_async()
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    seq = _next_save_seq(path)
    meta = {"version": 4, "save_seq": seq, "arrays": {}}
    local_files = []  # (fname, np chunk)
    for k, v in state_dict.items():
        arr = _unwrap(v)
        if not isinstance(arr, jax.Array):
            arr = jax.numpy.asarray(arr)
        chunks = _global_chunks(arr)
        meta["arrays"][k] = {
            "shape": list(arr.shape), "dtype": str(arr.dtype),
            "chunks": [{"bounds": [list(b) for b in info["bounds"]],
                        "file": _chunk_file(seq, info["owner_process"], k,
                                            ck)}
                       for ck, info in sorted(chunks.items())]}
        by_dev = {s.device.id: s for s in arr.addressable_shards}
        for ck, info in chunks.items():
            if info["owner_process"] != rank:
                continue
            if info["owner_device"] == -1:  # unsharded host array
                data = np.asarray(arr)
            else:
                data = np.asarray(by_dev[info["owner_device"]].data)
            local_files.append((_chunk_file(seq, rank, k, ck), data))

    def write():
        digests = {}
        for fname, data in local_files:
            _IO_RETRY.call(_atomic_write_npy, path, fname, data,
                           op="ckpt.chunk_write")
            digests[fname] = _sha256(data)
        if rank == coordinator_rank:
            # integrity: record the sha256 of every chunk this process
            # wrote (in the single-host regime that is every chunk;
            # chunks owned by other hosts load unverified — see
            # RESILIENCE.md)
            for amesh in meta["arrays"].values():
                for chunk in amesh["chunks"]:
                    if chunk["file"] in digests:
                        chunk["sha256"] = digests[chunk["file"]]
            _IO_RETRY.call(_replace_metadata, path, meta,
                           op="ckpt.metadata_replace")
            _gc_stale_chunks(path, meta)

    if async_save:
        # non-daemon: interpreter shutdown joins it, so the checkpoint can
        # never be truncated by process exit
        t = threading.Thread(target=write, daemon=False)
        t.start()
        _async_tasks.append(t)
    else:
        write()


class _ShardFileCache:
    """Memory-maps chunk .npy files on demand: a loading host touches only
    the chunks overlapping its destination blocks, never whole shard files,
    and nothing is unpickled. Each file is verified against its recorded
    sha256 the first time it is opened; missing/truncated/corrupt files
    raise CheckpointCorruptionError with the file name."""

    def __init__(self, path, digests=None):
        self.path = path
        self._digests = digests or {}
        self._files = {}

    def get(self, fname):
        if fname not in self._files:
            try:
                arr = np.load(os.path.join(self.path, fname), mmap_mode="r",
                              allow_pickle=False)
            except FileNotFoundError:
                raise CheckpointCorruptionError(
                    fname, "file is missing") from None
            except (OSError, ValueError, EOFError) as e:
                raise CheckpointCorruptionError(
                    fname, f"unreadable ({e})") from None
            expect = self._digests.get(fname)
            if expect is not None:
                try:
                    got = _sha256(arr)
                except (OSError, ValueError) as e:   # mmap read of a
                    raise CheckpointCorruptionError(  # truncated tail
                        fname, f"short read ({e})") from None
                if got != expect:
                    raise CheckpointCorruptionError(
                        fname, f"sha256 mismatch (recorded {expect[:12]}…, "
                        f"found {got[:12]}…)")
            self._files[fname] = arr
        return self._files[fname]


def _assemble_region(key, amesh, cache, bounds, dtype):
    """Build the [start:stop)-region of array `key` from overlapping chunks."""
    shape = tuple(b - a for a, b in bounds)
    out = np.empty(shape, dtype=dtype)
    filled = 0
    for chunk in amesh["chunks"]:
        cb = [tuple(x) for x in chunk["bounds"]]
        # intersection of chunk bounds with requested bounds
        inter = [(max(a0, b0), min(a1, b1))
                 for (a0, a1), (b0, b1) in zip(cb, bounds)]
        if any(a >= b for a, b in inter):
            continue
        data = cache.get(chunk["file"])
        src = tuple(slice(a - c0, b - c0)
                    for (a, b), (c0, _) in zip(inter, cb))
        dst = tuple(slice(a - r0, b - r0)
                    for (a, b), (r0, _) in zip(inter, bounds))
        out[dst] = data[src]
        filled += int(np.prod([b - a for a, b in inter]))
    if filled < int(np.prod(shape)):
        raise ValueError(
            f"checkpoint for '{key}' does not cover region {bounds} "
            f"(filled {filled} of {int(np.prod(shape))} elements)")
    return out


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, offload=False):
    """Fill `state_dict` tensors in place, resharding each saved array onto
    the tensor's CURRENT sharding (which may come from a different mesh than
    the one that saved it). reference: checkpoint/load_state_dict.py."""
    _count("checkpoint_loads_total")  # the resume path of elastic recovery
    _wait_async()
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    version = meta.get("version")
    if version not in (3, 4):
        raise ValueError(
            f"checkpoint at {path} has format version {version}; this "
            "loader reads versions 3/4 (per-chunk .npy files). Re-save the "
            "checkpoint with the current save_state_dict.")
    digests = {chunk["file"]: chunk["sha256"]
               for amesh in meta["arrays"].values()
               for chunk in amesh["chunks"] if "sha256" in chunk}
    cache = _ShardFileCache(path, digests)
    for k, v in state_dict.items():
        if k not in meta["arrays"]:
            raise KeyError(f"checkpoint missing key {k}")
        amesh = meta["arrays"][k]
        saved_dtype = np.dtype(amesh["dtype"])
        arr = _unwrap(v)
        target_sharding = getattr(arr, "sharding", None)
        shape = tuple(amesh["shape"])
        if isinstance(v, Tensor) and tuple(arr.shape) != shape:
            raise ValueError(
                f"shape mismatch for '{k}': checkpoint {shape} vs "
                f"model {tuple(arr.shape)}")
        if target_sharding is None or not isinstance(arr, jax.Array):
            full = _assemble_region(k, amesh, cache,
                                    tuple((0, s) for s in shape), saved_dtype)
            new = jax.numpy.asarray(full, dtype=arr.dtype)
        else:
            # per-device blocks assembled from overlapping saved chunks
            index_map = target_sharding.devices_indices_map(shape)
            blocks = []
            devs = []
            for dev in target_sharding.addressable_devices:
                bounds = _norm_index(index_map[dev], shape)
                block = _assemble_region(k, amesh, cache, bounds, saved_dtype)
                blocks.append(jax.device_put(
                    block.astype(arr.dtype), dev))
                devs.append(dev)
            new = jax.make_array_from_single_device_arrays(
                shape, target_sharding, blocks)
        if isinstance(v, Tensor):
            v._data = new
        else:
            state_dict[k] = new
    return state_dict
