"""Distributed checkpoint: sharded save / reshard-on-load.

reference capability: python/paddle/distributed/checkpoint/ —
save_state_dict.py:145 (per-rank shard files + global metadata),
save_state_dict.py:117 (dedup of replicated tensors across ranks),
metadata.py (LocalTensorMetadata/LocalTensorIndex), load_state_dict.py
(reshard-on-load onto a different mesh/placement), async save :46.

TPU-native design: each process writes ONLY the array chunks it owns
(`arr.addressable_shards`, one replica per distinct chunk globally — the
owner is the lowest (process_index, device_id) holder, computed
deterministically on every host from the sharding, no communication).
Every chunk is its own `.npy` file (reference uses per-tensor files +
metadata, save_state_dict.py:145): loads memory-map only the chunks that
overlap the destination blocks, and nothing goes through pickle.
`metadata.json` records the global layout: per-array shape/dtype and the
chunk → file map. Load assembles each destination device's block from the
overlapping saved chunks and builds the array with
`jax.make_array_from_single_device_arrays`, so a checkpoint saved from a
(dp=8) mesh loads onto a (dp=2,mp=2) mesh — or a single chip — without any
rank reading bytes it does not need.

Durability: every file is written to a temp name then os.replace'd
(atomic), metadata goes last, and async save runs on a NON-daemon thread —
process exit joins it, so a returned save_state_dict(async_save=True) can
never leave a truncated checkpoint.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading

import numpy as np

import jax

from ...framework.core import Tensor

__all__ = ["save_state_dict", "load_state_dict"]


def _count(name):
    """Checkpoint telemetry (observability catalog); the save/load path
    never fails over a metrics problem."""
    try:
        from ...observability.catalog import metric
        metric(name).inc()
    except Exception:  # noqa: BLE001
        pass

_async_tasks: list[threading.Thread] = []


def _wait_async():
    global _async_tasks
    for t in _async_tasks:
        t.join()
    _async_tasks = []


def _unwrap(v):
    return v._data if isinstance(v, Tensor) else v


def _norm_index(index, shape):
    """Normalize a shard index (tuple of slices) to ((start, stop), ...)."""
    out = []
    for dim, sl in enumerate(index):
        start = 0 if sl.start is None else int(sl.start)
        stop = shape[dim] if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _chunk_key(bounds):
    return ";".join(f"{a}:{b}" for a, b in bounds)


def _global_chunks(arr):
    """Deterministic global chunk map for a (possibly sharded) jax.Array.

    Returns {chunk_key: {"bounds": ..., "owner_process": int,
                         "owner_device": int}} — every host computes the same
    owners from the sharding alone (analog of the reference's cross-rank
    dedup, save_state_dict.py:117, done without communication).
    """
    shape = arr.shape
    sharding = getattr(arr, "sharding", None)
    if sharding is None:
        bounds = tuple((0, s) for s in shape)
        return {_chunk_key(bounds): {"bounds": bounds, "owner_process": 0,
                                     "owner_device": -1}}
    groups = {}
    for dev, index in sharding.devices_indices_map(shape).items():
        bounds = _norm_index(index, shape)
        key = _chunk_key(bounds)
        cur = groups.get(key)
        rank = (getattr(dev, "process_index", 0), dev.id)
        if cur is None or rank < (cur["owner_process"], cur["owner_device"]):
            groups[key] = {"bounds": bounds, "owner_process": rank[0],
                           "owner_device": rank[1]}
    return groups


def _chunk_file(owner_rank, key, chunk_key):
    """Deterministic per-chunk file name — every host derives the same map
    from (array name, bounds, owner) without communication."""
    h = hashlib.sha1(f"{key}\x00{chunk_key}".encode()).hexdigest()[:16]
    return f"r{owner_rank}_{h}.npy"


def _atomic_write_npy(path, fname, data):
    tmp = os.path.join(path, fname + ".tmp")
    np.save(tmp, data, allow_pickle=False)
    # np.save appends .npy to names without it
    os.replace(tmp + ".npy" if not tmp.endswith(".npy") else tmp,
               os.path.join(path, fname))


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    async_save=False):
    """Sharded save: this process writes only chunks it owns, one .npy file
    per chunk, each atomically renamed into place; metadata.json last.

    reference: checkpoint/save_state_dict.py:145.
    """
    _count("checkpoint_saves_total")
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    meta = {"version": 3, "arrays": {}}
    local_files = []  # (fname, np chunk)
    for k, v in state_dict.items():
        arr = _unwrap(v)
        if not isinstance(arr, jax.Array):
            arr = jax.numpy.asarray(arr)
        chunks = _global_chunks(arr)
        meta["arrays"][k] = {
            "shape": list(arr.shape), "dtype": str(arr.dtype),
            "chunks": [{"bounds": [list(b) for b in info["bounds"]],
                        "file": _chunk_file(info["owner_process"], k, ck)}
                       for ck, info in sorted(chunks.items())]}
        by_dev = {s.device.id: s for s in arr.addressable_shards}
        for ck, info in chunks.items():
            if info["owner_process"] != rank:
                continue
            if info["owner_device"] == -1:  # unsharded host array
                data = np.asarray(arr)
            else:
                data = np.asarray(by_dev[info["owner_device"]].data)
            local_files.append((_chunk_file(rank, k, ck), data))

    def write():
        for fname, data in local_files:
            _atomic_write_npy(path, fname, data)
        if rank == coordinator_rank:
            tmp = os.path.join(path, "metadata.json.tmp")
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, os.path.join(path, "metadata.json"))

    if async_save:
        # non-daemon: interpreter shutdown joins it, so the checkpoint can
        # never be truncated by process exit
        t = threading.Thread(target=write, daemon=False)
        t.start()
        _async_tasks.append(t)
    else:
        write()


class _ShardFileCache:
    """Memory-maps chunk .npy files on demand: a loading host touches only
    the chunks overlapping its destination blocks, never whole shard files,
    and nothing is unpickled."""

    def __init__(self, path):
        self.path = path
        self._files = {}

    def get(self, fname):
        if fname not in self._files:
            self._files[fname] = np.load(
                os.path.join(self.path, fname), mmap_mode="r",
                allow_pickle=False)
        return self._files[fname]


def _assemble_region(key, amesh, cache, bounds, dtype):
    """Build the [start:stop)-region of array `key` from overlapping chunks."""
    shape = tuple(b - a for a, b in bounds)
    out = np.empty(shape, dtype=dtype)
    filled = 0
    for chunk in amesh["chunks"]:
        cb = [tuple(x) for x in chunk["bounds"]]
        # intersection of chunk bounds with requested bounds
        inter = [(max(a0, b0), min(a1, b1))
                 for (a0, a1), (b0, b1) in zip(cb, bounds)]
        if any(a >= b for a, b in inter):
            continue
        data = cache.get(chunk["file"])
        src = tuple(slice(a - c0, b - c0)
                    for (a, b), (c0, _) in zip(inter, cb))
        dst = tuple(slice(a - r0, b - r0)
                    for (a, b), (r0, _) in zip(inter, bounds))
        out[dst] = data[src]
        filled += int(np.prod([b - a for a, b in inter]))
    if filled < int(np.prod(shape)):
        raise ValueError(
            f"checkpoint for '{key}' does not cover region {bounds} "
            f"(filled {filled} of {int(np.prod(shape))} elements)")
    return out


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, offload=False):
    """Fill `state_dict` tensors in place, resharding each saved array onto
    the tensor's CURRENT sharding (which may come from a different mesh than
    the one that saved it). reference: checkpoint/load_state_dict.py."""
    _count("checkpoint_loads_total")  # the resume path of elastic recovery
    _wait_async()
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    version = meta.get("version")
    if version != 3:
        raise ValueError(
            f"checkpoint at {path} has format version {version}; this "
            "loader reads version 3 (per-chunk .npy files). Re-save the "
            "checkpoint with the current save_state_dict.")
    cache = _ShardFileCache(path)
    for k, v in state_dict.items():
        if k not in meta["arrays"]:
            raise KeyError(f"checkpoint missing key {k}")
        amesh = meta["arrays"][k]
        saved_dtype = np.dtype(amesh["dtype"])
        arr = _unwrap(v)
        target_sharding = getattr(arr, "sharding", None)
        shape = tuple(amesh["shape"])
        if isinstance(v, Tensor) and tuple(arr.shape) != shape:
            raise ValueError(
                f"shape mismatch for '{k}': checkpoint {shape} vs "
                f"model {tuple(arr.shape)}")
        if target_sharding is None or not isinstance(arr, jax.Array):
            full = _assemble_region(k, amesh, cache,
                                    tuple((0, s) for s in shape), saved_dtype)
            new = jax.numpy.asarray(full, dtype=arr.dtype)
        else:
            # per-device blocks assembled from overlapping saved chunks
            index_map = target_sharding.devices_indices_map(shape)
            blocks = []
            devs = []
            for dev in target_sharding.addressable_devices:
                bounds = _norm_index(index_map[dev], shape)
                block = _assemble_region(k, amesh, cache, bounds, saved_dtype)
                blocks.append(jax.device_put(
                    block.astype(arr.dtype), dev))
                devs.append(dev)
            new = jax.make_array_from_single_device_arrays(
                shape, target_sharding, blocks)
        if isinstance(v, Tensor):
            v._data = new
        else:
            state_dict[k] = new
    return state_dict
