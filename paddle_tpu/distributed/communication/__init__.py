"""paddle.distributed.communication — collective API package.

reference: python/paddle/distributed/communication/ — the collective
functions live flat on paddle.distributed here (collective.py); this
package provides the `stream` namespace for API parity.
"""

from . import stream  # noqa: F401

__all__ = ["stream"]
