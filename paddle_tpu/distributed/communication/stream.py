"""paddle.distributed.communication.stream — stream-ordered collectives.

reference: python/paddle/distributed/communication/stream/ (all_gather.py,
all_reduce.py, ... 11 entry points) — collectives enqueued on a chosen
CUDA stream with `use_calc_stream` picking compute-stream ordering.

TPU-native: XLA orders collectives by DATA DEPENDENCY inside the compiled
program — there is no user-visible stream to select, and the dependency
order IS the calc-stream order the reference's use_calc_stream=True asks
for. Each wrapper therefore runs the plain collective and returns its
(already completed) task handle; `use_calc_stream` is accepted and
ignored. reference semantics preserved: sync_op=False returns a waitable
task.
"""

from __future__ import annotations

from .. import collective as _c

__all__ = ["all_gather", "all_reduce", "alltoall", "alltoall_single",
           "broadcast", "reduce", "reduce_scatter", "recv", "scatter",
           "send", "gather"]


def _streamed(fn, *args, sync_op=True, use_calc_stream=False, **kwargs):
    if use_calc_stream and not sync_op:
        # reference contract (stream/all_reduce.py:152): calc-stream
        # ordering only exists for sync ops
        raise RuntimeError(
            "use_calc_stream can only be true in sync op behavior.")
    return fn(*args, sync_op=sync_op, **kwargs)


def all_reduce(tensor, op=_c.ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=False):
    return _streamed(_c.all_reduce, tensor, op, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)


def all_gather(tensor_or_tensor_list, tensor, group=None, sync_op=True,
               use_calc_stream=False):
    return _streamed(_c.all_gather, tensor_or_tensor_list, tensor, group,
                     sync_op=sync_op, use_calc_stream=use_calc_stream)


def alltoall(out_tensor_or_tensor_list, in_tensor_or_tensor_list, group=None,
             sync_op=True, use_calc_stream=False):
    return _streamed(_c.all_to_all, out_tensor_or_tensor_list,
                     in_tensor_or_tensor_list, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)


def alltoall_single(out_tensor, in_tensor, out_split_sizes=None,
                    in_split_sizes=None, group=None, sync_op=True,
                    use_calc_stream=False):
    return _streamed(_c.all_to_all_single, out_tensor, in_tensor,
                     out_split_sizes, in_split_sizes, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)


def broadcast(tensor, src=0, group=None, sync_op=True,
              use_calc_stream=False):
    return _streamed(_c.broadcast, tensor, src, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)


def reduce(tensor, dst=0, op=_c.ReduceOp.SUM, group=None, sync_op=True,
           use_calc_stream=False):
    return _streamed(_c.reduce, tensor, dst, op, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)


def reduce_scatter(tensor, tensor_or_tensor_list, op=_c.ReduceOp.SUM,
                   group=None, sync_op=True, use_calc_stream=False):
    return _streamed(_c.reduce_scatter, tensor, tensor_or_tensor_list, op,
                     group, sync_op=sync_op, use_calc_stream=use_calc_stream)


def scatter(tensor, tensor_or_tensor_list=None, src=0, group=None,
            sync_op=True, use_calc_stream=False):
    return _streamed(_c.scatter, tensor, tensor_or_tensor_list, src, group,
                     sync_op=sync_op, use_calc_stream=use_calc_stream)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True,
           use_calc_stream=False):
    return _streamed(_c.gather, tensor, gather_list, dst, group,
                     sync_op=sync_op, use_calc_stream=use_calc_stream)


def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=False):
    return _streamed(_c.send, tensor, dst, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)


def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    return _streamed(_c.recv, tensor, src, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)
