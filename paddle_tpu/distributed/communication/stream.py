"""paddle.distributed.communication.stream — stream-ordered collectives.

reference: python/paddle/distributed/communication/stream/ (all_gather.py,
all_reduce.py, ... 11 entry points) — collectives enqueued on a chosen
CUDA stream with `use_calc_stream` picking compute-stream ordering.

TPU-native: XLA orders collectives by DATA DEPENDENCY inside the compiled
program — there is no user-visible stream to select, and the dependency
order IS the calc-stream order the reference's use_calc_stream=True asks
for. Each wrapper therefore runs the plain collective and returns its
(already completed) task handle; `use_calc_stream` is accepted and
ignored. reference semantics preserved: sync_op=False returns a waitable
task.
"""

from __future__ import annotations

from ...framework.core import Tensor
from .. import collective as _c

__all__ = ["all_gather", "all_reduce", "alltoall", "alltoall_single",
           "broadcast", "reduce", "reduce_scatter", "recv", "scatter",
           "send", "gather"]


def _streamed(fn, *args, sync_op=True, use_calc_stream=False, **kwargs):
    if use_calc_stream and not sync_op:
        # reference contract (stream/all_reduce.py:152): calc-stream
        # ordering only exists for sync ops
        raise RuntimeError(
            "use_calc_stream can only be true in sync op behavior.")
    return fn(*args, sync_op=sync_op, **kwargs)


def _nranks(group):
    return group.nranks if group is not None else _c.get_world_size()


def _as_chunks(tensor, group, op_name):
    """Reference tensor flavor: one pre-sized tensor = nranks equal chunks
    along dim 0 (stream/all_gather.py tensor branch)."""
    from ...tensor.manipulation import split as _split
    n = _nranks(group)
    if int(tensor.shape[0]) % n != 0:
        raise ValueError(
            f"{op_name}: tensor dim 0 ({int(tensor.shape[0])}) must be "
            f"divisible by the group world size ({n})")
    return _split(tensor, n, axis=0)


def all_reduce(tensor, op=_c.ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=False):
    return _streamed(_c.all_reduce, tensor, op, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)


def all_gather(tensor_or_tensor_list, tensor, group=None, sync_op=True,
               use_calc_stream=False):
    if isinstance(tensor_or_tensor_list, Tensor):
        # tensor flavor: gather into a pre-sized tensor (nranks*d0 rows)
        from ...tensor.manipulation import concat
        out: list = []
        task = _streamed(_c.all_gather, out, tensor, group, sync_op=sync_op,
                         use_calc_stream=use_calc_stream)
        tensor_or_tensor_list._rebind(concat(out, 0))
        return task
    return _streamed(_c.all_gather, tensor_or_tensor_list, tensor, group,
                     sync_op=sync_op, use_calc_stream=use_calc_stream)


def alltoall(out_tensor_or_tensor_list, in_tensor_or_tensor_list, group=None,
             sync_op=True, use_calc_stream=False):
    if isinstance(in_tensor_or_tensor_list, Tensor) != \
            isinstance(out_tensor_or_tensor_list, Tensor):
        raise ValueError(
            "alltoall: input and output must both be tensors or both "
            "be tensor lists")
    if isinstance(in_tensor_or_tensor_list, Tensor):
        from ...tensor.manipulation import concat
        ins = _as_chunks(in_tensor_or_tensor_list, group, "alltoall")
        outs: list = []
        task = _streamed(_c.all_to_all, outs, ins, group, sync_op=sync_op,
                         use_calc_stream=use_calc_stream)
        out_tensor_or_tensor_list._rebind(concat(outs, 0))
        return task
    return _streamed(_c.all_to_all, out_tensor_or_tensor_list,
                     in_tensor_or_tensor_list, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)


def alltoall_single(out_tensor, in_tensor, out_split_sizes=None,
                    in_split_sizes=None, group=None, sync_op=True,
                    use_calc_stream=False):
    return _streamed(_c.all_to_all_single, out_tensor, in_tensor,
                     out_split_sizes, in_split_sizes, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)


def broadcast(tensor, src=0, group=None, sync_op=True,
              use_calc_stream=False):
    return _streamed(_c.broadcast, tensor, src, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)


def reduce(tensor, dst=0, op=_c.ReduceOp.SUM, group=None, sync_op=True,
           use_calc_stream=False):
    return _streamed(_c.reduce, tensor, dst, op, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)


def reduce_scatter(tensor, tensor_or_tensor_list, op=_c.ReduceOp.SUM,
                   group=None, sync_op=True, use_calc_stream=False):
    if isinstance(tensor_or_tensor_list, Tensor):
        tensor_or_tensor_list = _as_chunks(tensor_or_tensor_list, group,
                                           "reduce_scatter")
    return _streamed(_c.reduce_scatter, tensor, tensor_or_tensor_list, op,
                     group, sync_op=sync_op, use_calc_stream=use_calc_stream)


def scatter(tensor, tensor_or_tensor_list=None, src=0, group=None,
            sync_op=True, use_calc_stream=False):
    if isinstance(tensor_or_tensor_list, Tensor):
        tensor_or_tensor_list = _as_chunks(tensor_or_tensor_list, group,
                                           "scatter")
    return _streamed(_c.scatter, tensor, tensor_or_tensor_list, src, group,
                     sync_op=sync_op, use_calc_stream=use_calc_stream)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True,
           use_calc_stream=False):
    return _streamed(_c.gather, tensor, gather_list, dst, group,
                     sync_op=sync_op, use_calc_stream=use_calc_stream)


def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=False):
    return _streamed(_c.send, tensor, dst, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)


def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    return _streamed(_c.recv, tensor, src, group, sync_op=sync_op,
                     use_calc_stream=use_calc_stream)
