"""paddle.distributed.io. reference: python/paddle/distributed/io.py —
persistables save/load for distributed training."""

from __future__ import annotations

import os

__all__ = ["save_persistables", "load_persistables",
           "is_persistable"]


def is_persistable(var):
    return getattr(var, "persistable", False)


def save_persistables(executor, dirname, main_program=None, filename=None):
    """Save every persistable parameter of the program/layer."""
    target = main_program
    state = {}
    if target is not None and hasattr(target, "state_dict"):
        state = target.state_dict()
    os.makedirs(dirname, exist_ok=True)
    from ..framework.io_file import save
    save(state, os.path.join(dirname, filename or "persistables.pdparams"))


def load_persistables(executor, dirname, main_program=None, filename=None):
    from ..framework.io_file import load
    path = os.path.join(dirname, filename or "persistables.pdparams")
    state = load(path)
    if main_program is not None and hasattr(main_program, "set_state_dict"):
        main_program.set_state_dict(state)
    return state
