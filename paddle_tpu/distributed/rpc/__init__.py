"""paddle.distributed.rpc. reference: python/paddle/distributed/rpc/rpc.py
(init_rpc:..., rpc_sync, rpc_async, shutdown, get_worker_info) over C++ brpc
(paddle/fluid/distributed/rpc/).

TPU-native: brpc collapses to stdlib multiprocessing.connection (pickle over
TCP with authentication) for the control-plane RPC — tensor traffic belongs
on ICI via collectives, so RPC here is what it is in the reference's
use-cases: lightweight function shipping between hosts. Worker discovery
rides the native TCPStore (native/tcp_store.cc).
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
import secrets

from multiprocessing.connection import Client, Listener

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]


class WorkerInfo:
    def __init__(self, name, rank, ip, port):
        self.name = name
        self.rank = rank
        self.ip = ip
        self.port = port

    def __repr__(self):
        return (f"WorkerInfo(name={self.name}, rank={self.rank}, "
                f"ip={self.ip}, port={self.port})")


class _State:
    def __init__(self):
        self.name = None
        self.rank = None
        self.store = None
        self.listener = None
        self.serve_thread = None
        self.pool = None
        self.workers = {}
        self.auth = None
        self.stop = threading.Event()


_state = _State()


def _serve(listener, stop):
    while not stop.is_set():
        try:
            conn = listener.accept()
        except (OSError, EOFError):
            if stop.is_set():
                return
            continue

        def handle(c):
            try:
                while not stop.is_set():
                    try:
                        fn, args, kwargs = c.recv()
                    except (EOFError, OSError):
                        return
                    try:
                        result = ("ok", fn(*args, **kwargs))
                    except Exception as e:  # noqa: BLE001 — ship to caller
                        result = ("err", e)
                    c.send(result)
            finally:
                c.close()

        threading.Thread(target=handle, args=(conn,), daemon=True).start()


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """reference: distributed/rpc/rpc.py init_rpc."""
    from ..store import TCPStore
    rank = rank if rank is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", "0"))
    world_size = world_size or int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:8091")
    host, port = master_endpoint.rsplit(":", 1)
    _state.store = TCPStore(host, int(port), is_master=(rank == 0),
                            world_size=world_size, timeout=120)
    # per-job random authkey distributed through the rendezvous store (the
    # trust root, like the reference master endpoint) — RPC executes
    # callables, so connections must prove they joined this job
    if rank == 0:
        _state.auth = secrets.token_bytes(32)
        _state.store.set("__rpc/authkey", _state.auth)
    else:
        _state.auth = bytes(_state.store.get("__rpc/authkey"))
    # bind to this worker's interface (PADDLE_RPC_BIND_IP to widen), never
    # an unconditional 0.0.0.0
    my_ip = os.environ.get("POD_IP", "127.0.0.1")
    bind_ip = os.environ.get("PADDLE_RPC_BIND_IP", my_ip)
    _state.listener = Listener((bind_ip, 0), authkey=_state.auth)
    my_port = _state.listener.address[1]
    _state.name = name
    _state.rank = rank
    _state.pool = ThreadPoolExecutor(max_workers=8)
    _state.stop.clear()
    _state.serve_thread = threading.Thread(
        target=_serve, args=(_state.listener, _state.stop), daemon=True)
    _state.serve_thread.start()
    # register + discover everyone
    _state.store.set(f"__rpc/{rank}",
                     pickle.dumps(WorkerInfo(name, rank, my_ip, my_port)))
    for r in range(world_size):
        info = pickle.loads(_state.store.get(f"__rpc/{r}"))
        _state.workers[info.name] = info
        _state.workers[info.rank] = info
    _state.store.barrier("rpc_init")


def get_worker_info(name=None):
    if name is None:
        return _state.workers[_state.rank]
    return _state.workers[name]


def get_all_worker_infos():
    return sorted({id(v): v for v in _state.workers.values()}.values(),
                  key=lambda w: w.rank)


def _call(to, fn, args, kwargs, timeout):
    info = _state.workers[to]
    conn = Client((info.ip, info.port), authkey=_state.auth)
    try:
        conn.send((fn, args or (), kwargs or {}))
        if timeout and timeout > 0:
            if not conn.poll(timeout):
                raise TimeoutError(f"rpc to {to} timed out after {timeout}s")
        status, payload = conn.recv()
    finally:
        conn.close()
    if status == "err":
        raise payload
    return payload


def rpc_sync(to, fn, args=None, kwargs=None, timeout=-1):
    """reference: rpc.py rpc_sync — blocking remote call."""
    return _call(to, fn, args, kwargs, timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=-1) -> Future:
    """reference: rpc.py rpc_async — returns a Future (.wait() alias)."""
    fut = _state.pool.submit(_call, to, fn, args, kwargs, timeout)
    if not hasattr(fut, "wait"):
        fut.wait = fut.result
    return fut


def shutdown():
    """reference: rpc.py shutdown — barrier then teardown."""
    if _state.store is not None:
        try:
            _state.store.barrier("rpc_shutdown")
        except Exception:  # noqa: BLE001 — peers may already be gone
            pass
    _state.stop.set()
    if _state.listener is not None:
        try:
            _state.listener.close()
        except OSError:
            pass
    if _state.pool is not None:
        _state.pool.shutdown(wait=False)
    _state.__init__()
