"""Launcher. reference: python/paddle/distributed/launch/main.py:23.

On TPU pods the launch topology is fixed by the TPU runtime (one process per
host, all chips visible); `python -m paddle_tpu.distributed.launch train.py`
execs the script after jax.distributed bootstrap. Elastic ranges / etcd
rendezvous map to the TPU VM autoscaler + jax coordination service.
"""

from __future__ import annotations

import os
import runpy
import sys

__all__ = ["launch", "spawn"]


def launch():
    args = sys.argv[1:]
    script = None
    script_args = []
    i = 0
    while i < len(args):
        a = args[i]
        if a.startswith("--"):
            if "=" not in a and i + 1 < len(args) and not args[i + 1].startswith("--"):
                i += 1
        elif script is None:
            script = a
            script_args = args[i + 1:]
            break
        i += 1
    if script is None:
        print("usage: python -m paddle_tpu.distributed.launch [opts] script.py ...")
        sys.exit(1)
    from .parallel_env import init_parallel_env
    init_parallel_env()
    sys.argv = [script] + list(script_args)
    runpy.run_path(script, run_name="__main__")


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference: python/paddle/distributed/spawn.py. Single-controller JAX
    drives all local chips from one process, so spawn degenerates to a direct
    call (the mesh provides the parallelism)."""
    func(*args)
    return None
