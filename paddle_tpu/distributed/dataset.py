"""Slot-based datasets for PS-mode (recsys) training.

reference capability: python/paddle/distributed/fleet/dataset/dataset.py
(InMemoryDataset / QueueDataset over the C++ MultiSlotDataFeed,
fluid/framework/data_feed.cc) and fleet/data_generator (MultiSlotDataGenerator
— the pipe_command protocol that converts raw logs into the multislot text
format).

TPU-native redesign: no C++ data-feed threads or pipe fleets — the parsed
batches feed host-side PS pulls (distributed/ps) and one device transfer
per step, so the hot path is the parser, implemented over numpy with
optional pipe_command preprocessing via a subprocess per file. The
multislot TEXT FORMAT is kept verbatim (per line, per slot in use_var
order: `<n> <v_1> ... <v_n>`), as is the LoD contract: each sparse slot
yields (flat values, offsets) per batch — offsets[i]:offsets[i+1] are
instance i's ids, the reference's level-1 LoD.
"""

from __future__ import annotations

import shlex
import subprocess
import threading

import numpy as np

__all__ = ["InMemoryDataset", "QueueDataset", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class _SlotVar:
    """use_var entry: name + dtype ('int64' sparse feasigns or 'float32'
    dense values). Accepts plain strings (int64 slots) or objects with
    .name/.dtype (static.data Variables)."""

    def __init__(self, v):
        if isinstance(v, str):
            self.name, self.dtype = v, "int64"
        else:
            self.name = getattr(v, "name", str(v))
            dt = str(getattr(v, "dtype", "int64")).lower()
            self.dtype = "float32" if "float" in dt else "int64"


def _parse_line(line, slots):
    """One multislot line -> [(values ndarray)] in slot order, or None for
    malformed lines (the reference data feed skips them)."""
    toks = line.split()
    out = []
    i = 0
    try:
        for sv in slots:
            n = int(toks[i])
            i += 1
            vals = toks[i:i + n]
            if len(vals) != n:
                return None
            i += n
            if sv.dtype == "int64":
                out.append(np.array([int(x) for x in vals], np.int64))
            else:
                out.append(np.array([float(x) for x in vals], np.float32))
    except (ValueError, IndexError):
        return None
    if i != len(toks):
        # leftover tokens = slot-count mismatch between use_var and the
        # file; accepting the prefix would train on misaligned features
        return None
    return out


def _read_file_lines(path, pipe_command):
    if pipe_command in (None, "", "cat"):
        with open(path, "r") as f:
            yield from f
        return
    with open(path, "rb") as f:
        proc = subprocess.Popen(shlex.split(pipe_command), stdin=f,
                                stdout=subprocess.PIPE, text=True)
        assert proc.stdout is not None
        try:
            yield from proc.stdout
        finally:
            proc.stdout.close()
            rc = proc.wait()
        if rc != 0:
            # a crashed preprocessor must not silently truncate the data
            raise RuntimeError(
                f"pipe_command {pipe_command!r} exited {rc} on {path}")


def _batches(records, batch_size, slots):
    """Group parsed records into LoD batches:
    {name: (flat_values, offsets)} per batch."""
    for start in range(0, len(records), batch_size):
        chunk = records[start:start + batch_size]
        batch = {}
        for si, sv in enumerate(slots):
            parts = [r[si] for r in chunk]
            offsets = np.zeros(len(parts) + 1, np.int64)
            np.cumsum([p.size for p in parts], out=offsets[1:])
            flat = np.concatenate(parts) if parts else \
                np.zeros(0, np.int64 if sv.dtype == "int64" else np.float32)
            batch[sv.name] = (flat, offsets)
        yield batch


class InMemoryDataset:
    """reference: fleet/dataset/dataset.py InMemoryDataset — load files into
    RAM, shuffle, iterate LoD batches. Single-controller: global_shuffle
    degrades to local_shuffle (there is no trainer fleet to exchange with;
    each host shuffles its own shard of the filelist)."""

    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.pipe_command = "cat"
        self.slots: list[_SlotVar] = []
        self.filelist: list[str] = []
        self._records: list = []
        self._rng = np.random.RandomState(0)
        self._preload_thread = None

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command="cat", input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kwargs):
        self.batch_size = int(batch_size)
        self.thread_num = int(thread_num)
        self.pipe_command = pipe_command
        if use_var:
            self.set_use_var(use_var)
        return self

    def update_settings(self, **kwargs):
        for k, v in kwargs.items():
            if k == "use_var":
                self.set_use_var(v)
            elif hasattr(self, k):
                setattr(self, k, v)

    def set_use_var(self, var_list):
        self.slots = [_SlotVar(v) for v in var_list]

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    # --- loading ----------------------------------------------------------
    def _load(self):
        records = []
        for path in self.filelist:
            for line in _read_file_lines(path, self.pipe_command):
                line = line.strip()
                if not line:
                    continue
                rec = _parse_line(line, self.slots)
                if rec is not None:
                    records.append(rec)
        return records

    def load_into_memory(self, is_shuffle=False):
        if not self.slots:
            raise RuntimeError("init(use_var=...) before load_into_memory")
        self._records = self._load()
        if is_shuffle:
            self.local_shuffle()

    def preload_into_memory(self, thread_num=None):
        self._preload_thread = threading.Thread(
            target=lambda: setattr(self, "_records", self._load()),
            daemon=True)
        self._preload_thread.start()

    def wait_preload_done(self):
        if self._preload_thread is not None:
            self._preload_thread.join()
            self._preload_thread = None

    def local_shuffle(self):
        self._rng.shuffle(self._records)

    def global_shuffle(self, fleet=None, thread_num=12):
        # single-controller runtime: each host holds its own filelist
        # shard; shuffling it locally is the whole operation
        self.local_shuffle()

    def release_memory(self):
        self._records = []

    def get_memory_data_size(self, fleet=None):
        return len(self._records)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._records)

    # --- iteration --------------------------------------------------------
    def __iter__(self):
        yield from _batches(self._records, self.batch_size, self.slots)

    def __len__(self):
        return (len(self._records) + self.batch_size - 1) // self.batch_size


class QueueDataset(InMemoryDataset):
    """reference: QueueDataset — streaming iteration over the filelist
    without materializing records (no shuffle)."""

    def load_into_memory(self, is_shuffle=False):  # pragma: no cover
        raise RuntimeError("QueueDataset streams; use iteration directly "
                           "(reference: QueueDataset has no memory ops)")

    def preload_into_memory(self, thread_num=None):
        raise RuntimeError("QueueDataset streams; no memory ops")

    def wait_preload_done(self):
        raise RuntimeError("QueueDataset streams; no memory ops")

    def __len__(self):
        raise TypeError("QueueDataset streams; it has no length")

    def local_shuffle(self):
        raise RuntimeError("QueueDataset cannot shuffle (reference parity)")

    def global_shuffle(self, fleet=None, thread_num=12):
        raise RuntimeError("QueueDataset cannot shuffle (reference parity)")

    def __iter__(self):
        pending = []
        for path in self.filelist:
            for line in _read_file_lines(path, self.pipe_command):
                line = line.strip()
                if not line:
                    continue
                rec = _parse_line(line, self.slots)
                if rec is None:
                    continue
                pending.append(rec)
                if len(pending) == self.batch_size:
                    yield from _batches(pending, self.batch_size, self.slots)
                    pending = []
        if pending:
            yield from _batches(pending, self.batch_size, self.slots)


class MultiSlotDataGenerator:
    """reference: fleet/data_generator — user subclasses override
    generate_sample(line) returning an iterator of records
    [(slot_name, [values]), ...]; run_from_* emits the multislot text the
    datasets parse. The pipe protocol is preserved so generators written
    for the reference work unchanged."""

    def __init__(self):
        self._batch = 1

    def set_batch(self, batch_size):
        self._batch = int(batch_size)

    def generate_sample(self, line):  # pragma: no cover - abstract
        raise NotImplementedError(
            "subclass MultiSlotDataGenerator and implement generate_sample")

    def _format(self, record):
        parts = []
        for _name, values in record:
            vs = list(values)
            parts.append(str(len(vs)))
            parts.extend(str(v) for v in vs)
        return " ".join(parts)

    def _records_of(self, line):
        gen = self.generate_sample(line)
        if gen is None:
            return
        if callable(gen):  # reference allows returning a generator FUNC
            gen = gen()
        yield from gen

    def run_from_memory(self, lines=None):
        """Yield formatted multislot lines from in-memory raw lines."""
        out = []
        for line in (lines or [None]):
            for record in self._records_of(line):
                out.append(self._format(record))
        return out

    def run_from_stdin(self):  # pragma: no cover - exercised via pipe tests
        import sys
        for line in sys.stdin:
            for record in self._records_of(line):
                sys.stdout.write(self._format(record) + "\n")


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """String-valued slots flavor (reference keeps values as strings;
    formatting is identical)."""
