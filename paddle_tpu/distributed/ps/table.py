"""Sparse/dense parameter-server tables (host-resident row stores).

reference capability: paddle/fluid/distributed/ps/table/
(memory_sparse_table.cc, memory_dense_table.cc, memory_sparse_geo_table.cc).

TPU-native design: the table is HOST memory — on a TPU pod the dense model
lives in HBM under GSPMD, and the PS exists for the workload class the
reference built it for: sparse embedding tables larger than device memory.
Rows live in the native C++ store (native/ps_table.cc, ctypes with the GIL
released) with a bit-exact numpy fallback. Device interaction is pull ->
jnp gather -> compute -> push, see ps/embedding.py.
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from ... import _native
from .accessor import (CtrAccessor, SparseAdaGradRule, _RuleBase,
                       deterministic_init, deterministic_init_batch)

__all__ = ["SparseTable", "DenseTable"]


def _as_ids(ids) -> np.ndarray:
    a = np.asarray(ids)
    if a.dtype != np.uint64:
        a = a.astype(np.uint64)
    return np.ascontiguousarray(a.reshape(-1))


class SparseTable:
    """id -> embedding row store with a per-row optimizer rule.

    Native-backed when the toolchain built (default); the numpy fallback is
    semantically identical (same deterministic miss-init, same rules).
    """

    def __init__(self, emb_dim: int, accessor: CtrAccessor | None = None,
                 use_native: bool | None = None):
        self.emb_dim = int(emb_dim)
        self.accessor = accessor or CtrAccessor(SparseAdaGradRule())
        rule = self.accessor.rule
        # RLock: the entry-admission gate wraps contains()/pull()/apply
        # under one critical section (gated tables serialize pull vs push
        # — a two-step contains+read would otherwise race a concurrent
        # admission and mask a freshly stored row with its init values)
        self._lock = threading.RLock()
        if use_native is None:
            use_native = _native.available
        self._native = bool(use_native) and _native.available
        # feature-admission policy (reference entry_attr.py): probationary
        # ids live only in this counter until the policy admits them — the
        # row store never sees a rejected feature. The counter is bounded
        # (FIFO eviction) so permanently-rejected id streams cannot bloat
        # the host dict the way they would have bloated the table.
        self._entry = self.accessor.entry
        self._probation: dict[int, int] = {}
        self._probation_cap = 1_000_000
        if self._native:
            self._h = _native.lib().pt_ps_table_new(
                self.emb_dim, rule.rule_id, rule.learning_rate,
                rule.initial_range, rule.eps, rule.beta1, rule.beta2)
            if not self._h:
                raise RuntimeError("pt_ps_table_new failed")
        else:
            # fallback store: id -> [w, slots, meta(show, click, unseen)]
            self._rows: dict[int, list[np.ndarray]] = {}

    # --- fallback helpers --------------------------------------------------
    def _row(self, fid: int, create: bool):
        r = self._rows.get(fid)
        if r is None and create:
            rule = self.accessor.rule
            r = [deterministic_init(fid, self.emb_dim, rule.initial_range),
                 rule.init_slots(self.emb_dim),
                 np.zeros(3, np.float32)]
            self._rows[fid] = r
        return r

    def contains(self, ids) -> np.ndarray:
        """Membership mask (no row creation)."""
        ids = _as_ids(ids)
        if self._native:
            out = np.empty(ids.size, np.uint8)
            _native.lib().pt_ps_table_contains(
                self._h, ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                ids.size, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
            return out.astype(bool)
        with self._lock:
            return np.array([fid in self._rows for fid in ids.tolist()],
                            bool)

    # --- entry-admission gate (reference entry_attr.py) --------------------
    def _gate_writes(self, ids, payload):
        """Filter a gradient-bearing write down to admitted occurrences and
        update probation counters. Caller holds self._lock. In-batch
        duplicates: the occurrence that crosses the threshold admits the id
        for the REST of the batch too (no stale counter is left behind)."""
        present = self.contains(ids)
        keep = present.copy()
        newly: set[int] = set()
        counts = getattr(self._entry, "needs_count", True)
        for i in np.nonzero(~present)[0]:
            fid = int(ids[i])
            if fid in newly:
                keep[i] = True
                continue
            n = self._probation.get(fid, 0) + 1
            if self._entry.admit(fid, n):
                self._probation.pop(fid, None)
                newly.add(fid)
                keep[i] = True
            elif counts:
                if fid not in self._probation and \
                        len(self._probation) >= self._probation_cap:
                    self._probation.pop(next(iter(self._probation)))
                self._probation[fid] = n
        if keep.all():
            return ids, payload
        return (np.ascontiguousarray(ids[keep]),
                np.ascontiguousarray(payload[keep]))

    # --- core ops ----------------------------------------------------------
    def pull(self, ids, init_on_miss: bool = True) -> np.ndarray:
        ids = _as_ids(ids)
        if self._entry is not None and init_on_miss:
            # probationary ids read their would-be init without entering
            # the store; the entry policy admits rows on gradient writes
            # only. Locked: a push admitting between contains() and the
            # raw read must not be masked by init values.
            with self._lock:
                present = self.contains(ids)
                out = self.pull(ids, init_on_miss=False)
            missing = np.nonzero(~present)[0]
            if missing.size:
                out[missing] = deterministic_init_batch(
                    ids[missing], self.emb_dim,
                    self.accessor.rule.initial_range)
            return out
        out = np.empty((ids.size, self.emb_dim), np.float32)
        if self._native:
            _native.lib().pt_ps_table_pull(
                self._h, ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                ids.size, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                1 if init_on_miss else 0)
            return out
        with self._lock:
            for i, fid in enumerate(ids.tolist()):
                r = self._row(fid, init_on_miss)
                if r is None:
                    out[i] = 0.0
                else:
                    out[i] = r[0]
                    r[2][2] = 0.0  # unseen_days reset
        return out

    def push(self, ids, grads) -> None:
        ids = _as_ids(ids)
        grads = np.ascontiguousarray(
            np.asarray(grads, np.float32).reshape(ids.size, self.emb_dim))
        if self._entry is not None:
            # gate + apply under one lock so gated pulls see either the
            # pre-admission or post-apply state, never a half state
            with self._lock:
                ids, grads = self._gate_writes(ids, grads)
                if ids.size == 0:
                    return
                self._apply_push(ids, grads)
            return
        self._apply_push(ids, grads)

    def _apply_push(self, ids, grads) -> None:
        if self._native:
            _native.lib().pt_ps_table_push(
                self._h, ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                ids.size,
                grads.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            return
        rule = self.accessor.rule
        with self._lock:
            for i, fid in enumerate(ids.tolist()):
                r = self._row(fid, True)
                rule.apply(r[0], r[1], grads[i])

    def merge(self, ids, deltas) -> None:
        """Additive weight merge (geo-SGD delta application; reference
        memory_sparse_geo_table.cc) — bypasses the optimizer rule. Geo
        workers deliver their training updates through here, so the entry
        gate applies exactly as it does for push."""
        ids = _as_ids(ids)
        deltas = np.ascontiguousarray(
            np.asarray(deltas, np.float32).reshape(ids.size, self.emb_dim))
        if self._entry is not None:
            with self._lock:
                ids, deltas = self._gate_writes(ids, deltas)
                if ids.size == 0:
                    return
                self._apply_merge(ids, deltas)
            return
        self._apply_merge(ids, deltas)

    def _apply_merge(self, ids, deltas) -> None:
        if self._native:
            _native.lib().pt_ps_table_merge(
                self._h, ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                ids.size,
                deltas.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            return
        with self._lock:
            for i, fid in enumerate(ids.tolist()):
                self._row(fid, True)[0] += deltas[i]

    def assign(self, ids, rows) -> None:
        ids = _as_ids(ids)
        rows = np.ascontiguousarray(
            np.asarray(rows, np.float32).reshape(ids.size, self.emb_dim))
        if self._native:
            _native.lib().pt_ps_table_assign(
                self._h, ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                ids.size,
                rows.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            return
        with self._lock:
            for i, fid in enumerate(ids.tolist()):
                self._row(fid, True)[0][:] = rows[i]

    # --- lifecycle ---------------------------------------------------------
    def __len__(self) -> int:
        if self._native:
            return int(_native.lib().pt_ps_table_size(self._h))
        with self._lock:
            return len(self._rows)

    def keys(self) -> np.ndarray:
        if self._native:
            n = len(self)
            out = np.empty(n, np.uint64)
            got = _native.lib().pt_ps_table_keys(
                self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                n)
            return out[:got]
        with self._lock:
            return np.fromiter(self._rows.keys(), np.uint64,
                               count=len(self._rows))

    def add_show_click(self, ids, shows, clicks) -> None:
        ids = _as_ids(ids)
        shows = np.ascontiguousarray(np.asarray(shows, np.float32).reshape(-1))
        clicks = np.ascontiguousarray(
            np.asarray(clicks, np.float32).reshape(-1))
        if self._entry is not None:
            # stats never admit: update existing rows only (admission is a
            # gradient-write decision; un-admitted features drop stats)
            with self._lock:
                present = self.contains(ids)
                if not present.all():
                    ids = np.ascontiguousarray(ids[present])
                    shows = np.ascontiguousarray(shows[present])
                    clicks = np.ascontiguousarray(clicks[present])
                if ids.size == 0:
                    return
                return self._apply_show_click(ids, shows, clicks)
        self._apply_show_click(ids, shows, clicks)

    def _apply_show_click(self, ids, shows, clicks) -> None:
        if self._native:
            _native.lib().pt_ps_table_add_show_click(
                self._h, ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                ids.size,
                shows.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                clicks.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            return
        with self._lock:
            for i, fid in enumerate(ids.tolist()):
                m = self._row(fid, True)[2]
                m[0] += shows[i]
                m[1] += clicks[i]

    def decay(self, rate: float | None = None) -> None:
        rate = self.accessor.show_decay_rate if rate is None else float(rate)
        if self._native:
            _native.lib().pt_ps_table_decay(self._h, rate)
            return
        with self._lock:
            for r in self._rows.values():
                r[2][0] *= rate
                r[2][1] *= rate
                r[2][2] += 1.0

    def shrink(self) -> int:
        acc = self.accessor
        if self._native:
            return int(_native.lib().pt_ps_table_shrink(
                self._h, acc.shrink_show_threshold, acc.shrink_unseen_days))
        with self._lock:
            dead = [fid for fid, r in self._rows.items()
                    if r[2][0] < acc.shrink_show_threshold
                    and r[2][2] >= acc.shrink_unseen_days]
            for fid in dead:
                del self._rows[fid]
            return len(dead)

    def save(self, path: str) -> None:
        if self._native:
            rc = _native.lib().pt_ps_table_save(self._h, path.encode())
            if rc != 0:
                raise IOError(f"ps table save failed rc={rc}: {path}")
            return
        import os
        with self._lock:
            ids = np.fromiter(self._rows.keys(), np.uint64,
                              count=len(self._rows))
            # atomic replace: a failed save must not destroy the previous
            # checkpoint. Explicit .npz suffix keeps np.savez from
            # renaming the temp file.
            tmp = path + ".tmp.npz"
            np.savez(tmp, ids=ids,
                     w=np.stack([self._rows[int(i)][0] for i in ids])
                     if ids.size else np.zeros((0, self.emb_dim), np.float32),
                     slots=np.stack([self._rows[int(i)][1] for i in ids])
                     if ids.size else np.zeros((0, 0), np.float32),
                     meta=np.stack([self._rows[int(i)][2] for i in ids])
                     if ids.size else np.zeros((0, 3), np.float32))
            target = path if path.endswith(".npz") else path + ".npz"
            os.replace(tmp, target)

    def load(self, path: str) -> None:
        if self._native:
            rc = _native.lib().pt_ps_table_load(self._h, path.encode())
            if rc != 0:
                raise IOError(f"ps table load failed rc={rc}: {path}")
            return
        with np.load(path if path.endswith(".npz") else path + ".npz") as z:
            with self._lock:
                for i, fid in enumerate(z["ids"].tolist()):
                    self._rows[fid] = [z["w"][i].copy(), z["slots"][i].copy(),
                                       z["meta"][i].copy()]

    def __del__(self):  # pragma: no cover - interpreter teardown ordering
        try:
            if getattr(self, "_native", False) and getattr(self, "_h", None):
                _native.lib().pt_ps_table_free(self._h)
                self._h = None
        except Exception:
            pass


class DenseTable:
    """Versioned dense parameter block (reference memory_dense_table.cc).

    On TPU the dense path belongs to GSPMD; this exists for PS-mode parity:
    small dense params (biases, batch-norm stats) that recsys jobs keep on
    the server. Updates are plain SGD on the server; workers pull snapshots.
    """

    def __init__(self, shape, learning_rate: float = 0.05):
        self.value = np.zeros(shape, np.float32)
        self.learning_rate = float(learning_rate)
        self.version = 0
        self._lock = threading.Lock()

    def pull(self) -> tuple[np.ndarray, int]:
        with self._lock:
            return self.value.copy(), self.version

    def push(self, grad) -> None:
        g = np.asarray(grad, np.float32).reshape(self.value.shape)
        with self._lock:
            self.value -= self.learning_rate * g
            self.version += 1

    def assign(self, value) -> None:
        v = np.asarray(value, np.float32).reshape(self.value.shape)
        with self._lock:
            self.value[:] = v
            self.version += 1
