"""paddle.distributed.ps — parameter-server training for sparse models.

reference capability: paddle/fluid/distributed/ps/ (~55k LoC of brpc
services, sparse/dense/geo tables, accessors) + python/paddle/distributed/ps
(the_one_ps.py runtime).

TPU-native redesign (NOT a port — see each module's docstring):
  - the row store is native C++ (native/ps_table.cc) behind ctypes, striped
    hash shards with per-row optimizer state; rules are the accessor
    (accessor.py: naive/adagrad/adam + CTR decay/shrink policy)
  - transport is the framework's authenticated RPC over the native
    TCPStore, with an in-process fast path (service.py)
  - workers interact through dedup'd pull/push (embedding.py PsEmbedding
    for eager, PsBatch for compiled static-shape steps); geo-async SGD is
    a local shadow table pushing weight deltas (service.GeoWorkerCache)
  - dense parameters do NOT ride the PS on TPU: they live in HBM under
    GSPMD — the PS carries exactly what exceeds device memory: sparse
    embedding rows (DESIGN.md records this split)
"""

from .accessor import (CountFilterEntry, CtrAccessor, ProbabilityEntry,
                       ShowClickEntry, SparseAdaGradRule, SparseAdamRule,
                       SparseNaiveSGDRule)
from .embedding import PsBatch, PsEmbedding, ps_sparse_embedding
from .service import (GeoWorkerCache, LocalChannel, PsClient, PsServer,
                      RpcChannel, TableConfig, serve_tables)
from .table import DenseTable, SparseTable
from .the_one_ps import TheOnePs, from_env

__all__ = [
    "CountFilterEntry", "ProbabilityEntry", "ShowClickEntry",
    "CtrAccessor", "SparseAdaGradRule", "SparseAdamRule",
    "SparseNaiveSGDRule", "PsBatch", "PsEmbedding", "ps_sparse_embedding",
    "GeoWorkerCache", "LocalChannel", "PsClient", "PsServer", "RpcChannel",
    "TableConfig", "serve_tables", "DenseTable", "SparseTable", "TheOnePs",
    "from_env",
]
