"""Device-side sparse embedding over a PS table.

reference capability: the PS-mode sparse embedding
(python/paddle/static/nn/common.py sparse_embedding +
fluid/distributed/ps wrapper/fleet.cc PullSparse/PushSparse around the op).

TPU-native design: two paths.

1. Eager (`PsEmbedding`): pull -> device gather -> compute; the backward is
   a PyLayer whose vjp aggregates per-unique-id cotangents host-side and
   pushes them to the servers. Per-batch dedup means each row crosses
   host<->device once regardless of repetition.

2. Compiled (`PsBatch`): the TPU-idiomatic pattern for jit train steps.
   Host IO cannot live inside a traced program, so the step is
       prepare(ids)  ->  jit(step)(rows, inv, ...)  ->  complete(drows)
   with the unique-row buffer padded to a STATIC capacity so one executable
   serves every batch (XLA static shapes; re-compilation would dwarf the
   lookup). Padding rows are zero and their pushed gradients are dropped.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...autograd import PyLayer
from ...framework.core import Tensor
from ...nn import Layer

__all__ = ["PsEmbedding", "ps_sparse_embedding", "PsBatch"]


def _pull_unique(source, table_id, uniq):
    if hasattr(source, "pull_unique"):
        return source.pull_unique(table_id, uniq)
    return source.pull(uniq)  # GeoWorkerCache binds its table_id


def _push_unique(source, table_id, uniq, agg):
    if hasattr(source, "push_unique"):
        source.push_unique(table_id, uniq, agg)
    else:
        source.push(uniq, agg)


class _PsLookup(PyLayer):
    @staticmethod
    def forward(ctx, anchor, ids, source, table_id, emb_dim):
        ids_np = np.asarray(ids._data if isinstance(ids, Tensor) else ids)
        shape = ids_np.shape
        uniq, inv = np.unique(ids_np.reshape(-1).astype(np.uint64),
                              return_inverse=True)
        rows = _pull_unique(source, table_id, uniq)
        out = jnp.asarray(rows)[jnp.asarray(inv)].reshape(
            shape + (emb_dim,))
        ctx.ps_state = (source, table_id, uniq, inv, emb_dim)
        # anchor (a trainable scalar, always 0) keeps the node on the tape:
        # integer ids carry no gradient, so without it autograd would prune
        # the backward that performs the push
        return Tensor(out + anchor._data.astype(out.dtype))

    @staticmethod
    def backward(ctx, grad):
        source, table_id, uniq, inv, emb_dim = ctx.ps_state
        g = np.asarray(grad._data, np.float32).reshape(-1, emb_dim)
        agg = np.zeros((uniq.size, emb_dim), np.float32)
        np.add.at(agg, inv, g)
        _push_unique(source, table_id, uniq, agg)
        return Tensor(jnp.zeros((1,), jnp.float32))  # d anchor


class PsEmbedding(Layer):
    """Eager sparse embedding backed by a PS client or geo cache.

    forward(ids) pulls the batch's unique rows, gathers on device; backward
    pushes aggregated row gradients (the server applies the table's rule).
    """

    def __init__(self, embedding_dim: int, source, table_id: int = 0,
                 name: str | None = None):
        super().__init__()
        self.emb_dim = int(embedding_dim)
        self.source = source
        self.table_id = int(table_id)
        # see _PsLookup.forward: tape anchor, mathematically zero
        from ...nn import initializer as I
        self.anchor = self.create_parameter(
            (1,), dtype="float32", default_initializer=I.Constant(0.0))

    def forward(self, ids):
        return _PsLookup.apply(self.anchor, ids, self.source, self.table_id,
                               self.emb_dim)


def ps_sparse_embedding(ids, source, emb_dim: int, table_id: int = 0,
                        anchor: Tensor | None = None):
    """Functional flavor of PsEmbedding (no push on backward unless an
    anchor with stop_gradient=False is supplied)."""
    if anchor is None:
        anchor = Tensor(jnp.zeros((1,), jnp.float32), stop_gradient=False)
    return _PsLookup.apply(anchor, ids, source, table_id, emb_dim)


class PsBatch:
    """Static-shape pull/push bracket around a compiled train step.

    Usage:
        batch = PsBatch(client, table_id, emb_dim, capacity=4096)
        rows, inv = batch.prepare(ids)          # host: pull + pad
        loss, drows = jit_step(rows, inv, ...)  # device: gather via take
        batch.complete(drows)                   # host: aggregate + push

    Inside the jitted step, `embed = rows[inv]` (jnp.take) reconstructs the
    per-position embeddings; `drows` must be the cotangent w.r.t. `rows`
    (jax.grad gives it for free), already summed over duplicate positions
    by the gather's transpose.
    """

    def __init__(self, source, table_id: int, emb_dim: int, capacity: int):
        self.source = source
        self.table_id = int(table_id)
        self.emb_dim = int(emb_dim)
        self.capacity = int(capacity)
        self._uniq = None

    def prepare(self, ids):
        ids_np = np.asarray(ids).reshape(-1)
        uniq, inv = np.unique(ids_np.astype(np.uint64), return_inverse=True)
        if uniq.size > self.capacity:
            raise ValueError(
                f"batch has {uniq.size} unique ids > PsBatch capacity "
                f"{self.capacity}; raise capacity (one-time recompile)")
        rows = _pull_unique(self.source, self.table_id, uniq)
        padded = np.zeros((self.capacity, self.emb_dim), np.float32)
        padded[:uniq.size] = rows
        inv_padded = np.zeros(ids_np.size, np.int32)
        inv_padded[:] = inv  # padding rows are never referenced by inv
        self._uniq = uniq
        return jnp.asarray(padded), jnp.asarray(inv_padded)

    def complete(self, drows) -> None:
        if self._uniq is None:
            raise RuntimeError("PsBatch.complete before prepare")
        uniq = self._uniq
        self._uniq = None
        g = np.asarray(drows, np.float32)[:uniq.size]
        _push_unique(self.source, self.table_id, uniq, g)
