"""TheOnePs: parameter-server runtime orchestration.

reference: python/paddle/distributed/ps/the_one_ps.py (TheOnePSRuntime —
builds tables from the program, starts brpc servers/workers, barriers) and
fleet's PS-mode lifecycle (init_server/run_server/init_worker/stop_worker).

TPU-native redesign: no brpc, no program parsing. Tables are declared
explicitly (TableConfig); transport is either in-process (single host — the
common TPU-pod case, where every host runs one server shard AND one
trainer) or the framework RPC layer for dedicated server hosts. The dense
model never touches the PS: it lives in HBM under GSPMD. Only sparse
embedding rows ride this path.
"""

from __future__ import annotations

import os
import threading

from .service import (LocalChannel, PsClient, PsServer, RpcChannel,
                      TableConfig, serve_tables)

__all__ = ["TheOnePs", "TableConfig"]


def server_name(server_id: int) -> str:
    """RPC worker name contract for PS server processes: a server process
    must call rpc.init_rpc(name=server_name(i)) so trainers can route to
    it (fleet.init_worker connects by these names)."""
    return f"ps_server_{server_id}"


class TheOnePs:
    """PS lifecycle engine.

    Local mode (default): `start_local()` creates all server shards
    in-process; `client` routes to them directly. This is the single-host
    topology — and on a TPU pod each host typically runs its shard next to
    its trainer, so "local" covers the pod case per host.

    RPC mode: server processes call `start_server(server_id)` after
    rpc.init_rpc; trainer processes call `connect([server worker names])`.
    """

    def __init__(self, table_configs: list[TableConfig],
                 num_servers: int = 1, served_name: str = "default"):
        self.configs = list(table_configs)
        self.num_servers = int(num_servers)
        self.served_name = served_name
        self.client: PsClient | None = None
        self.servers: list[PsServer] = []
        self._stop = threading.Event()

    def emb_dims(self) -> dict[int, int]:
        return {c.table_id: c.emb_dim for c in self.configs}

    # --- local (in-process shards) ---------------------------------------
    def start_local(self) -> PsClient:
        self.servers = [PsServer(s, self.num_servers, self.configs)
                        for s in range(self.num_servers)]
        self.client = PsClient([LocalChannel(s) for s in self.servers])
        return self.client

    # --- rpc (dedicated server hosts) ------------------------------------
    def start_server(self, server_id: int) -> PsServer:
        """Call on a server process AFTER
        rpc.init_rpc(name=server_name(server_id)) — trainers connect by
        that name (see server_name above)."""
        from .. import rpc as _rpc
        try:
            me = _rpc.get_worker_info()
        except Exception:
            me = None
        if me is not None and me.name != server_name(server_id):
            raise RuntimeError(
                f"PS server {server_id} must init_rpc with name "
                f"'{server_name(server_id)}', got '{me.name}' — trainers "
                "route by this name")
        server = PsServer(server_id, self.num_servers, self.configs)
        serve_tables(server, self.served_name)
        self.servers = [server]
        return server

    def run_server(self) -> None:
        """Block until stop() — requests are served by the RPC threads."""
        self._stop.wait()

    def connect(self, server_names: list[str]) -> PsClient:
        dims = self.emb_dims()
        self.client = PsClient([
            RpcChannel(n, self.served_name, dims) for n in server_names])
        return self.client

    # --- lifecycle --------------------------------------------------------
    def stop(self) -> None:
        self._stop.set()

    def save(self, dirname: str) -> None:
        if self.client is not None:
            self.client.save(dirname)
        elif self.servers:
            for s in self.servers:
                s.save(dirname)

    def load(self, dirname: str) -> None:
        if self.client is not None:
            self.client.load(dirname)
        elif self.servers:
            for s in self.servers:
                s.load(dirname)


def from_env(table_configs: list[TableConfig]) -> TheOnePs:
    """Build from the reference's PS cluster env layout
    (PADDLE_PSERVERS_IP_PORT_LIST / PADDLE_PSERVER_NUMS)."""
    eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
    n = int(os.environ.get("PADDLE_PSERVER_NUMS",
                           str(len(eps.split(",")) if eps else 1)))
    return TheOnePs(table_configs, num_servers=max(n, 1))
