"""PS service layer: sharded servers + routing client + geo-async cache.

reference capability: paddle/fluid/distributed/ps/service/
(brpc_ps_server.cc / ps_client.cc request routing, communicator.cc async
push batching) and table sharding by feature-id hash.

TPU-native redesign: no brpc. Transport is the framework's own RPC layer
(paddle_tpu.distributed.rpc — authenticated pickle-over-TCP riding the
native TCPStore rendezvous); for single-host topologies the channel is a
direct in-process call. Row ownership is hash(id) % num_servers computed
vectorized on the client; each server holds one SparseTable shard per
logical table. Tensor traffic stays off this path by design — embeddings
pulled here enter the device once per step as one dense gather input
(ps/embedding.py), everything dense rides ICI via GSPMD.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .accessor import CtrAccessor
from .table import DenseTable, SparseTable

__all__ = ["TableConfig", "PsServer", "PsClient", "LocalChannel",
           "RpcChannel", "GeoWorkerCache", "serve_tables"]

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def owner_of(ids: np.ndarray, num_servers: int) -> np.ndarray:
    """Row owner = mixed hash of the feature id, mod server count (stable
    across clients; uint64 wraparound is the mix)."""
    mixed = ids.astype(np.uint64) * _GOLDEN
    return ((mixed >> np.uint64(33)) % np.uint64(num_servers)).astype(
        np.int64)


class TableConfig:
    def __init__(self, table_id: int, emb_dim: int,
                 accessor: CtrAccessor | None = None):
        self.table_id = int(table_id)
        self.emb_dim = int(emb_dim)
        self.accessor = accessor or CtrAccessor()


class PsServer:
    """One PS shard: holds the local portion of every configured table."""

    def __init__(self, server_id: int, num_servers: int,
                 configs: list[TableConfig]):
        self.server_id = int(server_id)
        self.num_servers = int(num_servers)
        self.tables: dict[int, SparseTable] = {
            c.table_id: SparseTable(c.emb_dim, c.accessor) for c in configs}
        self.dense: dict[int, DenseTable] = {}

    # --- request handlers (bytes in/bytes out keeps RPC payloads flat) ----
    def pull(self, table_id: int, ids: np.ndarray) -> np.ndarray:
        return self.tables[table_id].pull(ids)

    def push(self, table_id: int, ids: np.ndarray,
             grads: np.ndarray) -> None:
        self.tables[table_id].push(ids, grads)

    def merge(self, table_id: int, ids: np.ndarray,
              deltas: np.ndarray) -> None:
        self.tables[table_id].merge(ids, deltas)

    def save(self, dirname: str) -> None:
        import os
        os.makedirs(dirname, exist_ok=True)
        for tid, t in self.tables.items():
            t.save(f"{dirname}/table{tid}.shard{self.server_id}")

    def load(self, dirname: str) -> None:
        for tid, t in self.tables.items():
            t.load(f"{dirname}/table{tid}.shard{self.server_id}")

    def stats(self) -> dict:
        return {tid: len(t) for tid, t in self.tables.items()}


# --------------------------------------------------------------------------
# module-global served instance: RPC calls resolve these by qualified name
# on the server process (pickle ships the function reference, not the code)
# --------------------------------------------------------------------------

_SERVED: dict[str, PsServer] = {}
_SERVED_LOCK = threading.Lock()


def serve_tables(server: PsServer, name: str = "default") -> None:
    with _SERVED_LOCK:
        _SERVED[name] = server


def _served(name: str) -> PsServer:
    s = _SERVED.get(name)
    if s is None:
        raise RuntimeError(f"no PS server '{name}' served in this process; "
                           "call ps.serve_tables() first")
    return s


def _remote_pull(name, table_id, ids_bytes, n):
    ids = np.frombuffer(ids_bytes, np.uint64, count=n)
    return _served(name).pull(table_id, ids).tobytes()


def _remote_push(name, table_id, ids_bytes, grads_bytes, n, dim):
    ids = np.frombuffer(ids_bytes, np.uint64, count=n)
    grads = np.frombuffer(grads_bytes, np.float32).reshape(n, dim)
    _served(name).push(table_id, ids, grads)
    return True


def _remote_merge(name, table_id, ids_bytes, deltas_bytes, n, dim):
    ids = np.frombuffer(ids_bytes, np.uint64, count=n)
    deltas = np.frombuffer(deltas_bytes, np.float32).reshape(n, dim)
    _served(name).merge(table_id, ids, deltas)
    return True


def _remote_save(name, dirname):
    _served(name).save(dirname)
    return True


def _remote_load(name, dirname):
    _served(name).load(dirname)
    return True


def _remote_stats(name):
    return _served(name).stats()


class LocalChannel:
    """Direct in-process channel (single-host PS, tests)."""

    def __init__(self, server: PsServer):
        self.server = server

    def pull(self, table_id, ids):
        return self.server.pull(table_id, ids)

    def push(self, table_id, ids, grads):
        self.server.push(table_id, ids, grads)

    def merge(self, table_id, ids, deltas):
        self.server.merge(table_id, ids, deltas)

    def save(self, dirname):
        self.server.save(dirname)

    def load(self, dirname):
        self.server.load(dirname)

    def stats(self):
        return self.server.stats()


class RpcChannel:
    """Cross-host channel over paddle_tpu.distributed.rpc."""

    def __init__(self, worker_name: str, served_name: str = "default",
                 emb_dims: dict[int, int] | None = None):
        self.worker = worker_name
        self.name = served_name
        self.emb_dims = emb_dims or {}

    def _dim(self, table_id):
        try:
            return self.emb_dims[table_id]
        except KeyError:
            raise KeyError(f"RpcChannel needs emb_dims[{table_id}] to "
                           "decode pull payloads") from None

    def pull(self, table_id, ids):
        from .. import rpc
        ids = np.ascontiguousarray(ids, np.uint64)
        raw = rpc.rpc_sync(self.worker, _remote_pull,
                           (self.name, table_id, ids.tobytes(), ids.size))
        return np.frombuffer(raw, np.float32).reshape(
            ids.size, self._dim(table_id)).copy()

    def push(self, table_id, ids, grads):
        from .. import rpc
        ids = np.ascontiguousarray(ids, np.uint64)
        g = np.ascontiguousarray(grads, np.float32)
        rpc.rpc_sync(self.worker, _remote_push,
                     (self.name, table_id, ids.tobytes(), g.tobytes(),
                      ids.size, g.shape[-1]))

    def merge(self, table_id, ids, deltas):
        from .. import rpc
        ids = np.ascontiguousarray(ids, np.uint64)
        d = np.ascontiguousarray(deltas, np.float32)
        rpc.rpc_sync(self.worker, _remote_merge,
                     (self.name, table_id, ids.tobytes(), d.tobytes(),
                      ids.size, d.shape[-1]))

    def save(self, dirname):
        from .. import rpc
        rpc.rpc_sync(self.worker, _remote_save, (self.name, dirname))

    def load(self, dirname):
        from .. import rpc
        rpc.rpc_sync(self.worker, _remote_load, (self.name, dirname))

    def stats(self):
        from .. import rpc
        return rpc.rpc_sync(self.worker, _remote_stats, (self.name,))


class PsClient:
    """Routes pulls/pushes to owner servers; dedups and pre-aggregates.

    reference: ps_client.cc PullSparse/PushSparse request fan-out; the
    communicator's gradient aggregation (communicator.cc) is the
    np.add.at pre-aggregation here — one row update per unique id per push
    regardless of how often it repeats in the batch.
    """

    def __init__(self, channels: list):
        self.channels = channels
        self.n = len(channels)
        self._pool = ThreadPoolExecutor(max_workers=max(2, self.n))

    def pull(self, table_id: int, ids) -> np.ndarray:
        ids = np.ascontiguousarray(np.asarray(ids).reshape(-1), np.uint64)
        uniq, inv = np.unique(ids, return_inverse=True)
        return self.pull_unique(table_id, uniq)[inv]

    def pull_unique(self, table_id: int, uniq_ids) -> np.ndarray:
        """Pull already-unique ids (the embedding layer dedups on device)."""
        uniq = np.ascontiguousarray(np.asarray(uniq_ids).reshape(-1),
                                    np.uint64)
        if uniq.size == 0:
            # delegate so the (0, emb_dim) width comes from the table
            return self.channels[0].pull(table_id, uniq)
        owners = owner_of(uniq, self.n)
        rows = None
        futs = {}
        for s in range(self.n):
            sel = np.nonzero(owners == s)[0]
            if sel.size == 0:
                continue
            futs[s] = (sel, self._pool.submit(
                self.channels[s].pull, table_id, uniq[sel]))
        for s, (sel, fut) in futs.items():
            part = fut.result()
            if rows is None:
                rows = np.empty((uniq.size, part.shape[1]), np.float32)
            rows[sel] = part
        return rows

    def push(self, table_id: int, ids, grads) -> None:
        ids = np.ascontiguousarray(np.asarray(ids).reshape(-1), np.uint64)
        grads = np.asarray(grads, np.float32).reshape(ids.size, -1)
        uniq, inv = np.unique(ids, return_inverse=True)
        agg = np.zeros((uniq.size, grads.shape[1]), np.float32)
        np.add.at(agg, inv, grads)
        self._push_unique(table_id, uniq, agg, "push")

    def push_unique(self, table_id: int, uniq_ids, grads) -> None:
        uniq = np.ascontiguousarray(np.asarray(uniq_ids).reshape(-1),
                                    np.uint64)
        g = np.asarray(grads, np.float32).reshape(uniq.size, -1)
        self._push_unique(table_id, uniq, g, "push")

    def merge(self, table_id: int, ids, deltas) -> None:
        ids = np.ascontiguousarray(np.asarray(ids).reshape(-1), np.uint64)
        d = np.asarray(deltas, np.float32).reshape(ids.size, -1)
        self._push_unique(table_id, ids, d, "merge")

    def _push_unique(self, table_id, uniq, payload, op):
        owners = owner_of(uniq, self.n)
        futs = []
        for s in range(self.n):
            sel = np.nonzero(owners == s)[0]
            if sel.size == 0:
                continue
            fn = getattr(self.channels[s], op)
            futs.append(self._pool.submit(fn, table_id, uniq[sel],
                                          payload[sel]))
        for f in futs:
            f.result()

    def save(self, dirname: str) -> None:
        for c in self.channels:
            c.save(dirname)

    def load(self, dirname: str) -> None:
        for c in self.channels:
            c.load(dirname)

    def stats(self) -> list[dict]:
        return [c.stats() for c in self.channels]


class GeoWorkerCache:
    """Geo-async SGD worker cache (reference memory_sparse_geo_table.cc +
    communicator GeoCommunicator): train against a LOCAL shadow table,
    every `geo_step` pushes accumulated weight DELTAS (not gradients) to
    the servers and refreshes the local rows — eventual consistency with a
    bounded staleness of geo_step optimizer steps."""

    def __init__(self, client: PsClient, table_id: int, emb_dim: int,
                 accessor: CtrAccessor | None = None, geo_step: int = 8):
        self.client = client
        self.table_id = int(table_id)
        self.emb_dim = int(emb_dim)
        self.local = SparseTable(emb_dim, accessor)
        self.base: dict[int, np.ndarray] = {}
        self.touched: set[int] = set()
        self.geo_step = int(geo_step)
        self._step = 0

    def pull(self, ids) -> np.ndarray:
        ids = np.ascontiguousarray(np.asarray(ids).reshape(-1), np.uint64)
        missing = np.array([i for i in np.unique(ids).tolist()
                            if i not in self.base], np.uint64)
        if missing.size:
            fresh = self.client.pull_unique(self.table_id, missing)
            self.local.assign(missing, fresh)
            for j, fid in enumerate(missing.tolist()):
                self.base[fid] = fresh[j].copy()
        return self.local.pull(ids)

    def push(self, ids, grads) -> None:
        ids = np.ascontiguousarray(np.asarray(ids).reshape(-1), np.uint64)
        if any(int(i) not in self.base for i in np.unique(ids).tolist()):
            self.pull(ids)  # establish base rows for delta computation
        self.local.push(ids, grads)
        self.touched.update(ids.tolist())
        self._step += 1
        if self._step % self.geo_step == 0:
            self.sync()

    def sync(self) -> None:
        if not self.touched:
            return
        ids = np.fromiter(self.touched, np.uint64, count=len(self.touched))
        cur = self.local.pull(ids)
        base = np.stack([self.base[i] for i in ids.tolist()])
        delta = cur - base
        self.client.merge(self.table_id, ids, delta)
        fresh = self.client.pull_unique(self.table_id, ids)
        self.local.assign(ids, fresh)
        for j, fid in enumerate(ids.tolist()):
            self.base[fid] = fresh[j].copy()
        self.touched.clear()
