"""Sparse-table accessors: per-row optimizer rules + CTR statistics config.

reference capability: paddle/fluid/distributed/ps/table/sparse_sgd_rule.cc
(SparseNaiveSGDRule / SparseAdaGradSGDRule / SparseAdamSGDRule) and
ctr_accessor.cc (show/click statistics, decay rates, shrink thresholds).

TPU-native redesign: the rule is a small config object whose id selects the
native C++ update kernel (native/ps_table.cc apply_rule); the numpy
implementations here are the executable specification — the fallback path
when the toolchain is absent and the parity oracle in tests/test_ps.py.
"""

from __future__ import annotations

import numpy as np

__all__ = ["SparseNaiveSGDRule", "SparseAdaGradRule", "SparseAdamRule",
           "CtrAccessor", "CountFilterEntry", "ProbabilityEntry",
           "ShowClickEntry"]

_M64 = (1 << 64) - 1


def _splitmix64(state: int):
    state = (state + 0x9E3779B97F4A7C15) & _M64
    z = state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M64
    return (z ^ (z >> 31)) & _M64, state


def deterministic_init_batch(feature_ids: np.ndarray, emb_dim: int,
                             initial_range: float) -> np.ndarray:
    """Vectorized bit-exact mirror of native init_row: one splitmix64
    stream per feature id -> uniform[-initial_range, initial_range). A
    never-pushed id pulls identical weights on every server and across
    save/load. Vectorized over ids (the probationary-pull hot path must
    not pay a per-id, per-dim Python loop)."""
    ids = np.asarray(feature_ids, np.uint64).reshape(-1)
    out = np.empty((ids.size, emb_dim), np.float32)
    s = ids ^ np.uint64(0xA5A5A5A55A5A5A5A)
    with np.errstate(over="ignore"):
        for d in range(emb_dim):
            s = s + np.uint64(0x9E3779B97F4A7C15)
            z = s ^ (s >> np.uint64(30))
            z = z * np.uint64(0xBF58476D1CE4E5B9)
            z ^= z >> np.uint64(27)
            z = z * np.uint64(0x94D049BB133111EB)
            z ^= z >> np.uint64(31)
            u = (z >> np.uint64(40)).astype(np.float32) / \
                np.float32(1 << 24)
            out[:, d] = (np.float32(2.0) * u - np.float32(1.0)) * \
                np.float32(initial_range)
    return out


def deterministic_init(feature_id: int, emb_dim: int,
                       initial_range: float) -> np.ndarray:
    """Scalar flavor of deterministic_init_batch (the executable spec the
    tests pin against the native store)."""
    s = int(feature_id) ^ 0xA5A5A5A55A5A5A5A
    out = np.empty(emb_dim, np.float32)
    for d in range(emb_dim):
        r, s = _splitmix64(s)
        u = np.float32(r >> 40) / np.float32(1 << 24)
        out[d] = (np.float32(2.0) * u - np.float32(1.0)) * \
            np.float32(initial_range)
    return out


class _RuleBase:
    rule_id: int = -1

    def __init__(self, learning_rate: float = 0.05,
                 initial_range: float = 0.0001, eps: float = 1e-8,
                 beta1: float = 0.9, beta2: float = 0.999):
        self.learning_rate = float(learning_rate)
        self.initial_range = float(initial_range)
        self.eps = float(eps)
        self.beta1 = float(beta1)
        self.beta2 = float(beta2)

    # --- executable spec (numpy fallback + test oracle) -------------------
    def slot_len(self, emb_dim: int) -> int:
        return 0

    def init_slots(self, emb_dim: int) -> np.ndarray:
        return np.zeros(self.slot_len(emb_dim), np.float32)

    def apply(self, w: np.ndarray, slots: np.ndarray,
              g: np.ndarray) -> None:
        raise NotImplementedError


class SparseNaiveSGDRule(_RuleBase):
    """reference: SparseNaiveSGDRule (sparse_sgd_rule.cc)."""
    rule_id = 0

    def apply(self, w, slots, g):
        w -= np.float32(self.learning_rate) * g


class SparseAdaGradRule(_RuleBase):
    """Per-dim adagrad. reference: SparseAdaGradSGDRule (sparse_sgd_rule.cc);
    design departure: the reference keeps one scalar g2sum per feature, here
    the accumulator is per-dimension (standard adagrad) — strictly more
    state, strictly better conditioning, and it vectorizes."""
    rule_id = 1

    def slot_len(self, emb_dim):
        return emb_dim

    def apply(self, w, slots, g):
        slots += g * g
        w -= np.float32(self.learning_rate) * g / \
            (np.sqrt(slots) + np.float32(self.eps))


class SparseAdamRule(_RuleBase):
    """reference: SparseAdamSGDRule (sparse_sgd_rule.cc). Slots: m, v and
    the per-row running beta powers (the reference stores beta pows per row
    too — sparse rows step at different times, so bias correction must be
    per-row)."""
    rule_id = 2

    def slot_len(self, emb_dim):
        return 2 * emb_dim + 2

    def init_slots(self, emb_dim):
        s = np.zeros(2 * emb_dim + 2, np.float32)
        s[2 * emb_dim + 0] = 1.0
        s[2 * emb_dim + 1] = 1.0
        return s

    def apply(self, w, slots, g):
        d = w.shape[0]
        m, v = slots[:d], slots[d:2 * d]
        b1, b2 = np.float32(self.beta1), np.float32(self.beta2)
        slots[2 * d + 0] *= b1
        slots[2 * d + 1] *= b2
        corr1 = np.float32(1.0) - slots[2 * d + 0]
        corr2 = np.float32(1.0) - slots[2 * d + 1]
        m[:] = b1 * m + (np.float32(1.0) - b1) * g
        v[:] = b2 * v + (np.float32(1.0) - b2) * g * g
        w -= np.float32(self.learning_rate) * (m / corr1) / \
            (np.sqrt(v / corr2) + np.float32(self.eps))


class CtrAccessor:
    """Bundle of rule + CTR lifecycle policy for one sparse table.

    reference: CtrCommonAccessor (ctr_accessor.cc) — show/click statistics
    with daily decay and threshold-based shrink of cold features; `entry`
    is the feature-admission policy (reference python/paddle/distributed/
    entry_attr.py CountFilterEntry/ProbabilityEntry/ShowClickEntry).
    """

    def __init__(self, rule: _RuleBase | None = None,
                 show_decay_rate: float = 0.98,
                 shrink_show_threshold: float = 0.1,
                 shrink_unseen_days: float = 7.0,
                 entry=None):
        self.rule = rule or SparseAdaGradRule()
        self.show_decay_rate = float(show_decay_rate)
        self.shrink_show_threshold = float(shrink_show_threshold)
        self.shrink_unseen_days = float(shrink_unseen_days)
        self.entry = entry


class CountFilterEntry:
    """Admit a feature into the table only after it was pushed `count_filter`
    times (reference entry_attr.py CountFilterEntry — keeps one-off ids from
    bloating the table)."""

    needs_count = True  # admit() depends on the probation counter

    def __init__(self, count_filter: int):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self.count_filter = int(count_filter)

    def admit(self, feature_id: int, seen_count: int) -> bool:
        return seen_count >= self.count_filter


class ProbabilityEntry:
    """Admit with fixed probability, deterministic per feature id
    (reference entry_attr.py ProbabilityEntry)."""

    needs_count = False  # decision is per-id, not per-occurrence

    def __init__(self, probability: float):
        if not 0.0 <= probability <= 1.0:
            raise ValueError("probability must be in [0, 1]")
        self.probability = float(probability)

    def admit(self, feature_id: int, seen_count: int) -> bool:
        r, _ = _splitmix64(int(feature_id) ^ 0xC0FFEE)
        return (r >> 11) / float(1 << 53) < self.probability


class ShowClickEntry:
    """Names the show/click input slots feeding the CTR statistics
    (reference entry_attr.py ShowClickEntry); admission is unconditional —
    the stats drive decay/shrink, not entry."""

    needs_count = False

    def __init__(self, show_name: str, click_name: str):
        self.show_name = str(show_name)
        self.click_name = str(click_name)

    def admit(self, feature_id: int, seen_count: int) -> bool:
        return True
