"""Process bootstrap + groups.

reference: python/paddle/distributed/parallel.py:978 init_parallel_env,
collective.py:151 _new_process_group_impl, TCPStore rendezvous
(parallel.py:1134), paddle/phi/core/distributed/store/tcp_store.h.

TPU-native: jax.distributed.initialize handles rendezvous (its coordination
service IS the TCPStore analog); on a single host it is a no-op. "Rank" maps
to jax.process_index(), and device-level parallelism is expressed with
meshes, not per-device OS processes — one process drives all local chips.
Groups are index sets over jax.devices() used to build sub-meshes.
"""

from __future__ import annotations

import os

import jax

_initialized = False
_groups: dict[int, "Group"] = {}
_next_group_id = 0


class Group:
    def __init__(self, ranks, gid, backend="xla"):
        self.ranks = list(ranks)
        self.id = gid
        self.backend = backend

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def world_size(self):
        return len(self.ranks)

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


def init_parallel_env():
    """reference: python/paddle/distributed/parallel.py:978. Multi-host: set
    PADDLE_MASTER/PADDLE_TRAINERS_NUM (or JAX_COORDINATOR_ADDRESS) and this
    calls jax.distributed.initialize; single-host it just records state."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get("PADDLE_MASTER")
    nproc = int(os.environ.get("PADDLE_TRAINERS_NUM", "0") or 0)
    if coord and nproc > 1:
        pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid)
        # host-level KV store for out-of-band coordination (checkpoint
        # election, health heartbeats) — the native TCPStore on master+1:
        # reference behavior of parallel.py:1134
        try:
            from .store import TCPStore
            host, port = coord.rsplit(":", 1)
            global _store
            _store = TCPStore(host, int(port) + 1, is_master=(pid == 0),
                              world_size=nproc, timeout=300)
        except Exception:  # noqa: BLE001 — store is auxiliary, not fatal
            _store = None
    _initialized = True
    _groups[0] = Group(list(range(get_world_size())), 0)
    return ParallelEnv()


_store = None


def get_store():
    """The host-coordination TCPStore (None on single-host runs)."""
    return _store


def is_initialized():
    return _initialized


def get_rank(group=None):
    if group is not None:
        return group.get_group_rank(jax.process_index())
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    # world = total devices when used for mesh math on one host
    return jax.process_count()


def get_group(gid=0):
    return _groups.get(gid)


def new_group(ranks=None, backend=None, timeout=None):
    global _next_group_id
    _next_group_id += 1
    g = Group(ranks if ranks is not None else list(range(get_world_size())),
              _next_group_id, backend or "xla")
    _groups[g.id] = g
    return g


def barrier(group=None):
    # XLA programs are bulk-synchronous; a host barrier only matters
    # multi-process, where jax.experimental.multihost_utils provides it.
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")


def destroy_process_group(group=None):
    global _initialized
    if group is None:
        _groups.clear()
        _initialized = False
    else:
        _groups.pop(group.id, None)


class ParallelEnv:
    """reference: python/paddle/distributed/parallel.py:ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def dev_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "127.0.0.1:6170")
        return eps.split(",")

    @property
    def nrings(self):
        return 1
