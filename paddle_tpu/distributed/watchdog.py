"""Hang/failure detection. reference:
paddle/phi/core/distributed/comm_task_manager.h:37 (CommTaskManager
background watchdog thread), nccl_comm_task.h:53 NCCLCommTask::IsTimeout,
and the launch-level elastic restart (fleet/elastic/manager.py).

TPU-native: XLA collectives are compiler-inserted, so there is no per-op
comm-task queue to watch. What can hang a multi-host SPMD program is a step
that never completes (peer died, network partition, data stall). The
watchdog therefore guards *steps*: a background thread fires when the gap
between step completions exceeds the timeout, dumps live Python stacks and
(optionally) aborts so the launcher can restart from the last checkpoint.
"""

from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time
import traceback

__all__ = ["Watchdog", "enable_comm_watchdog"]


class Watchdog:
    """Step-liveness watchdog.

    wd = Watchdog(timeout=300, on_timeout="dump")  # or "abort" / callable
    for batch in loader:
        with wd.step_guard():
            train_step(batch)
    """

    def __init__(self, timeout=600.0, on_timeout="dump", poll_interval=None,
                 name="train"):
        self.timeout = float(timeout)
        self.on_timeout = on_timeout
        self.name = name
        self._poll = poll_interval or max(1.0, self.timeout / 10)
        self._last_beat = None
        self._in_step_since = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._fired = False
        self._thread = None
        self._step_count = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._last_beat = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name=f"watchdog-{self.name}")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._poll * 2)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- step accounting -----------------------------------------------------
    def beat(self):
        """Mark liveness (a step completed)."""
        with self._lock:
            self._last_beat = time.monotonic()
            self._in_step_since = None
            self._step_count += 1

    class _StepGuard:
        def __init__(self, wd):
            self._wd = wd

        def __enter__(self):
            with self._wd._lock:
                self._wd._in_step_since = time.monotonic()
            return self

        def __exit__(self, *exc):
            self._wd.beat()

    def step_guard(self):
        if self._thread is None:
            self.start()
        return Watchdog._StepGuard(self)

    @property
    def step_count(self):
        return self._step_count

    @property
    def fired(self):
        return self._fired

    # -- detection -----------------------------------------------------------
    def _watch(self):
        while not self._stop.wait(self._poll):
            with self._lock:
                ref = self._in_step_since or self._last_beat
            if ref is None:
                continue
            gap = time.monotonic() - ref
            if gap > self.timeout:
                self._fired = True
                self._fire(gap)
                return

    def _fire(self, gap):
        msg = (f"[watchdog:{self.name}] no step completion for {gap:.0f}s "
               f"(timeout {self.timeout:.0f}s, {self._step_count} steps done) "
               f"— likely hung collective / dead peer / data stall")
        sys.stderr.write(msg + "\n")
        # dump all thread stacks — the analog of the reference's comm-task
        # diagnostics (comm_task_manager.cc timeout logs)
        for tid, frame in sys._current_frames().items():
            sys.stderr.write(f"--- thread {tid} ---\n")
            sys.stderr.write("".join(traceback.format_stack(frame)))
        sys.stderr.flush()
        if callable(self.on_timeout):
            self.on_timeout(self)
        elif self.on_timeout == "abort":
            faulthandler.dump_traceback()
            os._exit(124)  # noqa: SLF001 — deliberate hard abort for restart


_global_watchdog = None


def enable_comm_watchdog(timeout=None, on_timeout="dump"):
    """Process-wide watchdog, reading the reference's env knobs
    (FLAGS_pg_timeout analog: PADDLE_WATCHDOG_TIMEOUT seconds)."""
    global _global_watchdog
    if timeout is None:
        timeout = float(os.environ.get("PADDLE_WATCHDOG_TIMEOUT", "600"))
    if _global_watchdog is None:
        _global_watchdog = Watchdog(timeout=timeout, on_timeout=on_timeout,
                                    name="global").start()
    return _global_watchdog
