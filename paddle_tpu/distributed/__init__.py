"""paddle.distributed — GSPMD over jax.sharding.Mesh.

reference: python/paddle/distributed/ (148k LoC). The TPU-native collapse
(SURVEY.md §5): ProcessGroup/NCCLCommContext/TCPStore/launch →
jax.distributed.initialize + Mesh; collectives → psum/all_gather/ppermute
lowered by XLA onto ICI/DCN; DistTensor/reshard → NamedSharding +
device_put; SPMD rules → GSPMD propagation.
"""

from __future__ import annotations

from .parallel_env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, get_group, new_group,
    is_initialized, ParallelEnv, barrier, destroy_process_group,
)
from .placement import (  # noqa: F401
    Placement, Shard, Replicate, Partial, ProcessMesh,
)
from .api import (  # noqa: F401
    shard_tensor, dtensor_from_fn, reshard, shard_layer, shard_optimizer,
    unshard_dtensor, dtensor_from_local, shard_dataloader, to_distributed,
)
from .collective import (  # noqa: F401
    all_reduce, all_gather, all_gather_object, all_to_all, all_to_all_single,
    reduce_scatter, broadcast, reduce, scatter, gather, send, recv, isend,
    irecv, ReduceOp, P2POp, batch_isend_irecv, split, stream,
)
from .auto_parallel import (  # noqa: F401
    DistModel, Engine, Strategy, to_static)
from .auto_tuner import AutoTuner, TunerConfig  # noqa: F401
from .store import Store, TCPStore  # noqa: F401
from . import communication  # noqa: F401
from . import fleet  # noqa: F401
from . import checkpoint  # noqa: F401
from .sharding import group_sharded_parallel, save_group_sharded_model  # noqa: F401
from .parallel import DataParallel  # noqa: F401
from .launch import launch, spawn  # noqa: F401
from .watchdog import Watchdog, enable_comm_watchdog  # noqa: F401


def get_mesh():
    from .placement import _default_mesh
    return _default_mesh[0]


def set_mesh(mesh):
    from .placement import _default_mesh
    _default_mesh[0] = mesh

# ---------------------------------------------------------------------------
# reference-surface aliases + shims (python/paddle/distributed/__init__.py)
# ---------------------------------------------------------------------------

def alltoall(in_tensor_list, out_tensor_list, group=None, sync_op=True):
    """reference: paddle.distributed.alltoall — NOTE the reference argument
    order is (in, out), the reverse of torch-style all_to_all(out, in)."""
    return all_to_all(out_tensor_list, in_tensor_list, group=group,
                      sync_op=sync_op)


def alltoall_single(in_tensor, out_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """reference: paddle.distributed.alltoall_single (in, out) order."""
    return all_to_all_single(out_tensor, in_tensor,
                             out_split_sizes=out_split_sizes,
                             in_split_sizes=in_split_sizes, group=group,
                             sync_op=sync_op)
from .checkpoint import (  # noqa: F401
    save_state_dict, load_state_dict)
from . import io  # noqa: F401


class ReduceType:
    """reference: auto_parallel/placement_type ReduceType."""
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4


class ParallelMode:
    """reference: fleet ParallelMode enum."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class _ShardingStage:
    stage = 0

    def __init__(self, *a, **k):
        pass


class ShardingStage1(_ShardingStage):
    """Marker for Strategy/shard_optimizer (reference auto_parallel api)."""
    stage = 1


class ShardingStage2(_ShardingStage):
    stage = 2


class ShardingStage3(_ShardingStage):
    stage = 3


class DistAttr:
    """Legacy mesh+sharding-spec pair (reference:
    distributed/auto_parallel/api.py:144). Superseded by placements
    (Shard/Replicate/Partial) but kept constructible: shard_tensor accepts
    either flavor."""

    def __init__(self, mesh=None, sharding_specs=None):
        self.process_mesh = mesh
        self.sharding_specs = list(sharding_specs or [])

    def __repr__(self):
        return (f"DistAttr(mesh={self.process_mesh}, "
                f"sharding_specs={self.sharding_specs})")


def get_backend():
    """reference: get_backend — the comm backend name."""
    return "xla"


def is_available():
    import jax
    return len(jax.devices()) > 0


def wait(tensor, group=None, use_calc_stream=True):
    """reference: communication wait — XLA ops are ordered by data flow, so
    wait is a device sync."""
    if hasattr(tensor, "_data"):
        tensor._data.block_until_ready()
    return tensor


def broadcast_object_list(object_list, src=0, group=None):
    """Single-controller: every process already holds the object."""
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """reference contract (communication/scatter.py:91): out_object_list's
    CONTENT is replaced by this rank's scattered object."""
    out_object_list[:] = [in_object_list[0]] if in_object_list else []
    return out_object_list


def shard_scaler(scaler):
    """reference: auto_parallel shard_scaler — GradScaler state is already
    replicated arrays under GSPMD; returns the scaler unchanged."""
    return scaler


def gloo_init_parallel_env(*a, **k):
    raise NotImplementedError(
        "gloo is descoped on TPU (DESIGN.md): rendezvous rides the native "
        "TCPStore and collectives ride XLA/ICI")


def gloo_barrier(*a, **k):
    raise NotImplementedError("gloo is descoped on TPU (DESIGN.md)")


def gloo_release(*a, **k):
    raise NotImplementedError("gloo is descoped on TPU (DESIGN.md)")


# PS-mode datasets — real since r5, backed by distributed/dataset.py
# (multislot parsing + LoD batches feeding the TPU-native parameter
# server in distributed/ps)
from .dataset import InMemoryDataset, QueueDataset  # noqa: E402,F401


# feature-admission entry policies — real since r5, backed by the TPU-native
# parameter server (distributed/ps; reference entry_attr.py semantics)
from .ps.accessor import (CountFilterEntry, ProbabilityEntry,  # noqa: E402
                          ShowClickEntry)
