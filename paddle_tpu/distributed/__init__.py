"""paddle.distributed — GSPMD over jax.sharding.Mesh.

reference: python/paddle/distributed/ (148k LoC). The TPU-native collapse
(SURVEY.md §5): ProcessGroup/NCCLCommContext/TCPStore/launch →
jax.distributed.initialize + Mesh; collectives → psum/all_gather/ppermute
lowered by XLA onto ICI/DCN; DistTensor/reshard → NamedSharding +
device_put; SPMD rules → GSPMD propagation.
"""

from __future__ import annotations

from .parallel_env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, get_group, new_group,
    is_initialized, ParallelEnv, barrier, destroy_process_group,
)
from .placement import (  # noqa: F401
    Placement, Shard, Replicate, Partial, ProcessMesh,
)
from .api import (  # noqa: F401
    shard_tensor, dtensor_from_fn, reshard, shard_layer, shard_optimizer,
    unshard_dtensor, dtensor_from_local, shard_dataloader, to_distributed,
)
from .collective import (  # noqa: F401
    all_reduce, all_gather, all_gather_object, all_to_all, all_to_all_single,
    reduce_scatter, broadcast, reduce, scatter, gather, send, recv, isend,
    irecv, ReduceOp, P2POp, batch_isend_irecv, split, stream,
)
from .auto_parallel import (  # noqa: F401
    DistModel, Engine, Strategy, to_static)
from .auto_tuner import AutoTuner, TunerConfig  # noqa: F401
from .store import Store, TCPStore  # noqa: F401
from . import fleet  # noqa: F401
from . import checkpoint  # noqa: F401
from .sharding import group_sharded_parallel, save_group_sharded_model  # noqa: F401
from .parallel import DataParallel  # noqa: F401
from .launch import launch, spawn  # noqa: F401
from .watchdog import Watchdog, enable_comm_watchdog  # noqa: F401


def get_mesh():
    from .placement import _default_mesh
    return _default_mesh[0]


def set_mesh(mesh):
    from .placement import _default_mesh
    _default_mesh[0] = mesh
