"""Static auto-parallel: Strategy / Engine / DistModel / to_static.

reference: python/paddle/distributed/auto_parallel/static/engine.py:100
(Engine.fit/evaluate/predict over a distributed static program),
auto_parallel/api.py:2715 (to_static -> DistModel), strategy.py (Strategy
config tree).

TPU-native design: the reference's pipeline (program capture -> SPMD rule
propagation -> reshard insertion -> partitioned executor) collapses into
one jitted GSPMD train/eval step built by parallel.SpmdTrainer — sharding
rules choose parameter placements, XLA propagates/reshard-inserts, the
'executor' is the compiled step function.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..framework.core import Tensor

__all__ = ["Strategy", "Engine", "DistModel", "to_static"]


class _Cfg:
    """Attribute bag with defaults (mirrors the reference's config nodes)."""

    def __init__(self, **kw):
        self.__dict__.update(kw)

    def __repr__(self):
        return f"_Cfg({self.__dict__})"


class Strategy:
    """reference: auto_parallel/strategy.py — knobs that matter on TPU:

    - sharding: ZeRO over the 'sharding' mesh axis (enable, stage, degree)
    - recompute: activation rematerialization inside the jitted step
    - mp_degree / sep_degree: tensor / sequence-parallel mesh axis sizes
    - amp: bf16 parameter cast (TPU-native mixed precision)
    Gradient-merge/fused-passes analogs are XLA's job and have no knobs.
    """

    def __init__(self):
        self.sharding = _Cfg(enable=False, stage=1, degree=1)
        self.recompute = _Cfg(enable=False)
        self.amp = _Cfg(enable=False, dtype="bfloat16")
        self.mp_degree = 1
        self.sep_degree = 1
        self.dp_degree = None  # None = all remaining devices


def _build_mesh(strategy, n_devices=None):
    from ..parallel.spmd import create_mesh
    if n_devices is None:
        n_devices = len(jax.devices())
    mp = max(1, int(strategy.mp_degree))
    sep = max(1, int(strategy.sep_degree))
    shd = max(1, int(strategy.sharding.degree)) if strategy.sharding.enable \
        else 1
    rest = n_devices // (mp * sep * shd)
    dp = strategy.dp_degree or max(1, rest)
    return create_mesh(dp=dp, mp=mp, sep=sep, sharding=shd)


class Engine:
    """reference: auto_parallel/static/engine.py:100.

    engine = Engine(model, loss, optimizer, strategy=strategy)
    engine.fit(dataset, epochs, batch_size)   # compiled GSPMD steps
    engine.evaluate(dataset, batch_size)
    engine.predict(dataset, batch_size)
    """

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None, mesh=None, rules=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy or Strategy()
        self._mesh = mesh
        self._rules = rules
        self._trainer = None
        self._eval_fn = None
        self._pred_fn = None
        self.history = {"loss": []}

    # -- plumbing ----------------------------------------------------------
    def _jax_mesh(self):
        if self._mesh is None:
            self._mesh = _build_mesh(self._strategy)
        m = self._mesh
        return m.jax_mesh if hasattr(m, "jax_mesh") else m

    def _ensure_trainer(self):
        if self._trainer is not None:
            return self._trainer
        from ..parallel.spmd import DP_ONLY_RULES, SpmdTrainer
        st = self._strategy
        stage = st.sharding.stage if st.sharding.enable else 0
        self._trainer = SpmdTrainer(
            self._model, self._optimizer, self._jax_mesh(),
            self._rules or DP_ONLY_RULES,
            loss_fn=self._loss, batch_spec=P("dp"),
            remat=st.recompute.enable,
            dtype=st.amp.dtype if st.amp.enable else None,
            sharding_stage=stage)
        return self._trainer

    def _as_loader(self, data, batch_size, shuffle=False, drop_last=False):
        """drop_last only for fit (stable compiled shapes); eval/predict
        must see every sample."""
        from ..io import DataLoader
        if data is None:
            return None
        if hasattr(data, "__iter__") and not hasattr(data, "__getitem__"):
            return data  # already an iterable loader
        if isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last)

    def _get_eval_fn(self):
        """Jitted eval-mode loss (dropout off, BN running stats)."""
        if self._eval_fn is None:
            from ..parallel.functional import make_loss_fn
            self._eval_fn = jax.jit(
                make_loss_fn(self._model, self._loss, training=False))
        return self._eval_fn

    def _get_pred_fn(self):
        if self._pred_fn is None:
            from ..parallel.functional import functional_call

            def fwd(params, x, key):
                out = functional_call(self._model, params, x, rng_key=key,
                                      training=False)
                return out[1] if isinstance(out, (tuple, list)) else out

            self._pred_fn = jax.jit(fwd)
        return self._pred_fn

    @staticmethod
    def _arrays(batch):
        return jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else jnp.asarray(t),
            batch, is_leaf=lambda v: isinstance(v, Tensor))

    # -- public API --------------------------------------------------------
    def prepare(self, *args, **kwargs):
        """Static-graph warm-up parity shim: build the trainer eagerly."""
        self._ensure_trainer()
        return self

    def fit(self, train_data, epochs=1, batch_size=1, steps_per_epoch=None,
            verbose=0, **kw):
        trainer = self._ensure_trainer()
        loader = self._as_loader(train_data, batch_size, shuffle=True,
                                 drop_last=True)
        for epoch in range(epochs):
            for i, batch in enumerate(loader):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                loss = trainer.step(batch)
                self.history["loss"].append(float(loss))
                if verbose and i % max(1, verbose) == 0:
                    print(f"[engine] epoch {epoch} step {i} "
                          f"loss {float(loss):.4f}", flush=True)
        trainer.sync_to_model()
        return self.history

    def evaluate(self, valid_data, batch_size=1, steps=None, verbose=0, **kw):
        trainer = self._ensure_trainer()
        eval_fn = self._get_eval_fn()
        loader = self._as_loader(valid_data, batch_size)
        losses = []
        key = jax.random.key(0)
        for i, batch in enumerate(loader):
            if steps is not None and i >= steps:
                break
            losses.append(float(eval_fn(
                trainer.params, self._arrays(batch), key)))
        return {"loss": float(np.mean(losses)) if losses else float("nan")}

    def predict(self, test_data, batch_size=1, steps=None, **kw):
        trainer = self._ensure_trainer()
        self._get_pred_fn()
        loader = self._as_loader(test_data, batch_size)
        outs = []
        key = jax.random.key(0)
        for i, batch in enumerate(loader):
            if steps is not None and i >= steps:
                break
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
            outs.append(np.asarray(self._pred_fn(trainer.params, x, key)))
        return outs

    def save(self, path, training=True):
        self._ensure_trainer().sync_to_model()
        from ..framework.io_file import save
        save(self._model.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path):
        from ..framework.io_file import load
        self._model.set_state_dict(load(path + ".pdparams"))
        self._trainer = None  # re-shard from the restored weights

    @property
    def main_program(self):  # reference parity: inspectable artifact
        t = self._trainer
        return None if t is None or t._compiled is None else t._compiled


class DistModel:
    """reference: auto_parallel/api.py DistModel (returned by to_static).

    Callable: dist_model(*batch) runs ONE compiled step in the current mode
    ('train' -> loss + param update, 'eval' -> loss, 'predict' -> outputs).
    """

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None, mesh=None, rules=None):
        self._engine = Engine(layer, loss, optimizer, strategy=strategy,
                              mesh=mesh, rules=rules)
        self._loader = loader
        self._mode = "train" if optimizer is not None else (
            "eval" if loss is not None else "predict")

    def train(self):
        self._mode = "train"
        return self

    def eval(self):
        self._mode = "eval"
        return self

    def predict(self):
        self._mode = "predict"
        return self

    def __call__(self, *batch):
        eng = self._engine
        trainer = eng._ensure_trainer()
        if len(batch) == 1:
            batch = batch[0]
        if self._mode == "train":
            return trainer.step(batch)
        arrays = eng._arrays(batch)
        if self._mode == "eval":
            return eng._get_eval_fn()(trainer.params, arrays,
                                      jax.random.key(0))
        x = arrays[0] if isinstance(arrays, (tuple, list)) else arrays
        return eng._get_pred_fn()(trainer.params, x, jax.random.key(0))

    def state_dict(self, mode="all"):
        self._engine._ensure_trainer().sync_to_model()
        return self._engine._model.state_dict()

    @property
    def engine(self):
        return self._engine


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None,
              mesh=None, rules=None):
    """reference: auto_parallel/api.py:2715 — build the distributed,
    compiled form of a layer."""
    return DistModel(layer, loader, loss, optimizer, strategy=strategy,
                     mesh=mesh, rules=rules)
