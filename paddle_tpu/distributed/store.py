"""Rendezvous stores. reference: paddle/phi/core/distributed/store/
(store.h Store base, tcp_store.h:121 TCPStore) and the pybind surface
core.TCPStore used by python/paddle/distributed/parallel.py:1134.

The server/client are native C++ (native/tcp_store.cc) bound via ctypes;
blocking waits happen server-side on a condvar, exactly like the reference
(no client polling). A pure-Python in-process fallback covers environments
without a toolchain.
"""

from __future__ import annotations

import ctypes
import threading
import time

from ..resilience.faults import fault_point
from ..resilience.retry import RetryPolicy

__all__ = ["Store", "TCPStore", "ResilientStore"]


class Store:
    """Abstract KV store API (reference: store/store.h)."""

    def set(self, key: str, value):
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def add(self, key: str, amount: int) -> int:
        raise NotImplementedError

    def wait(self, key: str):
        raise NotImplementedError


class _PyStore:
    """In-process fallback with the same blocking semantics."""

    def __init__(self):
        self._data = {}
        self._cond = threading.Condition()

    def set(self, key, value):
        with self._cond:
            self._data[key] = bytes(value)
            self._cond.notify_all()

    def get(self, key, timeout_s):
        with self._cond:
            ok = self._cond.wait_for(lambda: key in self._data, timeout_s)
            if not ok:
                raise TimeoutError(f"TCPStore.get({key!r}) timed out")
            return self._data[key]

    def add(self, key, amount):
        with self._cond:
            cur = int(self._data.get(key, b"0") or b"0")
            cur += int(amount)
            self._data[key] = str(cur).encode()
            self._cond.notify_all()
            return cur

    def wait(self, key, timeout_s):
        with self._cond:
            if not self._cond.wait_for(lambda: key in self._data, timeout_s):
                raise TimeoutError(f"TCPStore.wait({key!r}) timed out")

    def delete_key(self, key):
        with self._cond:
            return self._data.pop(key, None) is not None

    def check(self, key):
        with self._cond:
            return key in self._data

    def num_keys(self):
        with self._cond:
            return len(self._data)


_py_stores = {}  # (host, port) -> _PyStore, for the in-process fallback


class TCPStore(Store):
    """reference: paddle/phi/core/distributed/store/tcp_store.h:121.

    The master rank (is_master=True) starts the native server; every rank
    (including the master) connects a client. All waits block server-side.
    """

    def __init__(self, host="127.0.0.1", port=6170, is_master=False,
                 world_size=1, timeout=900, stop_check_timeout=None):
        from .. import _native
        self._host = host
        self._port = int(port)
        self._timeout_s = timeout if timeout and timeout > 0 else 900
        self._world_size = world_size
        self._server = None
        self._client = None
        self._native = _native.available
        if not self._native:
            key = (host, self._port)
            if is_master:
                _py_stores[key] = _PyStore()
            elif key not in _py_stores:
                # the fallback is in-process only: a master in another
                # process can never appear here, so fail fast
                raise RuntimeError(
                    "TCPStore: native runtime unavailable and no in-process "
                    "master for this (host, port); the pure-Python fallback "
                    "cannot rendezvous across processes")
            self._store = _py_stores[key]
            return
        lib = _native.lib()
        if is_master:
            self._server = lib.pt_store_server_start(self._port)
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {self._port}")
            self._port = lib.pt_store_server_port(self._server)
        self._client = lib.pt_store_client_new(
            host.encode(), self._port, int(self._timeout_s * 1000))
        if not self._client:
            if self._server:
                lib.pt_store_server_stop(self._server)
            raise RuntimeError(
                f"TCPStore: cannot connect to {host}:{self._port}")

    # -- API ---------------------------------------------------------------
    @property
    def port(self):
        return self._port

    def set(self, key, value):
        fault_point("store.set", key=key)
        if isinstance(value, str):
            value = value.encode()
        if not self._native:
            return self._store.set(key, value)
        from .. import _native
        buf = (ctypes.c_uint8 * len(value)).from_buffer_copy(value) \
            if value else (ctypes.c_uint8 * 1)()
        rc = _native.lib().pt_store_set(self._client, key.encode(), buf,
                                        len(value))
        if rc != 0:
            raise RuntimeError(f"TCPStore.set({key!r}) failed")

    def get(self, key):
        fault_point("store.get", key=key)
        if not self._native:
            return self._store.get(key, self._timeout_s)
        from .. import _native
        lib = _native.lib()
        out_len = ctypes.c_int64()
        ptr = lib.pt_store_get(self._client, key.encode(),
                               int(self._timeout_s * 1000),
                               ctypes.byref(out_len))
        if not ptr or out_len.value < 0:
            raise TimeoutError(f"TCPStore.get({key!r}) timed out")
        try:
            return ctypes.string_at(ptr, out_len.value)
        finally:
            lib.pt_buffer_free(ptr)

    def add(self, key, amount):
        if not self._native:
            return self._store.add(key, amount)
        from .. import _native
        out = ctypes.c_int64()
        rc = _native.lib().pt_store_add(self._client, key.encode(),
                                        int(amount), ctypes.byref(out))
        if rc != 0:
            raise RuntimeError(f"TCPStore.add({key!r}) failed")
        return out.value

    def wait(self, key):
        if not self._native:
            return self._store.wait(key, self._timeout_s)
        from .. import _native
        rc = _native.lib().pt_store_wait(self._client, key.encode(),
                                         int(self._timeout_s * 1000))
        if rc != 0:
            raise TimeoutError(f"TCPStore.wait({key!r}) timed out")

    def delete_key(self, key):
        if not self._native:
            return self._store.delete_key(key)
        from .. import _native
        return _native.lib().pt_store_delete(self._client, key.encode()) == 0

    def check(self, key):
        if not self._native:
            return self._store.check(key)
        from .. import _native
        return _native.lib().pt_store_check(self._client, key.encode()) == 1

    def num_keys(self):
        if not self._native:
            return self._store.num_keys()
        from .. import _native
        return _native.lib().pt_store_num_keys(self._client)

    def barrier(self, tag="barrier"):
        """All world_size ranks arrive before any leaves. Reusable: each
        call on a tag advances a local round so keys never collide across
        rounds (every rank must call barrier the same number of times)."""
        rounds = getattr(self, "_barrier_rounds", None)
        if rounds is None:
            rounds = self._barrier_rounds = {}
        r = rounds.get(tag, 0)
        rounds[tag] = r + 1
        count = self.add(f"__barrier/{tag}/{r}/count", 1)
        if count == self._world_size:
            self.set(f"__barrier/{tag}/{r}/done", b"1")
        self.wait(f"__barrier/{tag}/{r}/done")

    def __del__(self):
        try:
            from .. import _native
            if self._native and _native.available:
                lib = _native.lib()
                if self._client:
                    lib.pt_store_client_free(self._client)
                    self._client = None
                if self._server:
                    lib.pt_store_server_stop(self._server)
                    self._server = None
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class ResilientStore(Store):
    """A Store whose ops survive transient failures: every call goes
    through a RetryPolicy (jittered backoff) and, optionally, a
    CircuitBreaker so a hard-down store fails fast instead of stalling
    every caller for the full timeout ladder.

        store = ResilientStore(TCPStore(...),
                               policy=RetryPolicy(max_attempts=4))

    Non-transient exceptions (anything outside policy.retry_on) pass
    through untouched. `wait` is retried too — a server-side blocking
    wait that dies from a connection blip is re-issued, not surfaced.
    """

    def __init__(self, store, policy=None, breaker=None):
        self._inner = store
        self._policy = policy or RetryPolicy()
        self._breaker = breaker  # e.g. CircuitBreaker(op="store")

    @property
    def inner(self):
        return self._inner

    def __getattr__(self, name):
        # drop-in wrapper: anything beyond the retried Store API (port,
        # barrier state, ...) comes straight from the wrapped store
        if name == "_inner":   # guard pre-__init__ probes from recursing
            raise AttributeError(name)
        return getattr(self._inner, name)

    def _call(self, op, fn, *args):
        if self._breaker is not None:
            return self._breaker.call(
                self._policy.call, fn, *args, op=op)
        return self._policy.call(fn, *args, op=op)

    def set(self, key, value):
        return self._call("store.set", self._inner.set, key, value)

    def get(self, key):
        return self._call("store.get", self._inner.get, key)

    def add(self, key, amount):
        return self._call("store.add", self._inner.add, key, amount)

    def wait(self, key):
        return self._call("store.wait", self._inner.wait, key)

    def delete_key(self, key):
        return self._call("store.delete", self._inner.delete_key, key)

    def check(self, key):
        return self._call("store.check", self._inner.check, key)

    def num_keys(self):
        return self._call("store.num_keys", self._inner.num_keys)

    def barrier(self, tag="barrier"):
        # the barrier protocol itself is add/set/wait on the inner store;
        # route it through the wrapped ops so each leg is retried
        rounds = getattr(self, "_barrier_rounds", None)
        if rounds is None:
            rounds = self._barrier_rounds = {}
        r = rounds.get(tag, 0)
        rounds[tag] = r + 1
        ws = getattr(self._inner, "_world_size", 1)
        count = self.add(f"__barrier/{tag}/{r}/count", 1)
        if count == ws:
            self.set(f"__barrier/{tag}/{r}/done", b"1")
        self.wait(f"__barrier/{tag}/{r}/done")
