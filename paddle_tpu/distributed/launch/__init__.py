"""Process launcher. reference: python/paddle/distributed/launch/main.py:23
(collective controller, master rendezvous, --nnodes elastic ranges,
--max_restart) and launch/controllers/collective.py.

TPU-native launch topology: ONE process per host drives all local chips
(single-controller JAX), so --nproc_per_node exists only for CPU-simulation
runs (each child gets JAX_PLATFORMS=cpu and a private rank). Rendezvous is
jax.distributed's coordination service, bootstrapped from --master; the
native TCPStore rides master_port+1 for out-of-band coordination
(parallel_env.init_parallel_env).

Usage:
  python -m paddle_tpu.distributed.launch train.py          # this host
  python -m paddle_tpu.distributed.launch --master host:port \
         --nnodes 4 --rank 0 train.py                       # multi-host
  python -m paddle_tpu.distributed.launch --nproc_per_node 4 \
         --backend cpu train.py                             # local simulation
"""

from __future__ import annotations

import argparse
import os
import runpy
import signal
import subprocess
import sys
import time

__all__ = ["launch", "spawn", "main"]


def _parse(argv):
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch",
                                add_help=True)
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER"),
                   help="host:port of the coordination service")
    p.add_argument("--nnodes", default="1",
                   help="node count, or elastic range lo:hi")
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    p.add_argument("--nproc_per_node", "--devices", dest="nproc_per_node",
                   default=None,
                   help="local worker processes (CPU simulation only)")
    p.add_argument("--backend", default=None, choices=[None, "cpu", "tpu"])
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--elastic_level", type=int, default=-1)
    p.add_argument("--job_id", default="default")
    p.add_argument("--log_dir", default=None)
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    # tolerate reference-launcher flags we don't implement (--log_level,
    # --gpus, --ips, --run_mode, ...): strip "--flag [value]" pairs that
    # argparse doesn't know before parsing, so the value isn't mistaken
    # for the script
    known = {a for action in p._actions for a in action.option_strings}
    filtered, ignored = [], []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--") and a.split("=")[0] not in known:
            ignored.append(a)
            nxt = argv[i + 1] if i + 1 < len(argv) else None
            # consume the next token as this flag's value — unless it looks
            # like the training script (a valueless boolean flag right
            # before the script must not swallow it)
            if "=" not in a and nxt is not None and not nxt.startswith("-") \
                    and not nxt.endswith((".py", ".sh")):
                ignored.append(nxt)
                i += 1
        elif a.startswith("-"):
            filtered.append(a)  # known flag (all take one value)
            if "=" not in a and i + 1 < len(argv):
                filtered.append(argv[i + 1])
                i += 1
        else:
            filtered.extend(argv[i:])  # script + its args: stop scanning
            break
        i += 1
    if ignored:
        sys.stderr.write(f"launch: ignoring unsupported flags {ignored}\n")
    return p.parse_args(filtered)


def _worker_count(spec):
    """--nproc_per_node N or --devices 0,1,2 (device-id list)."""
    s = str(spec)
    if "," in s:
        return len([d for d in s.split(",") if d != ""])
    return int(s)


def _nnodes_range(spec):
    if ":" in str(spec):
        lo, hi = str(spec).split(":")
        return int(lo), int(hi)
    return int(spec), int(spec)


def _run_local_procs(args):
    """CPU-simulation mode: one subprocess per simulated worker, restart on
    failure up to --max_restart (the launcher loop of launch/main.py)."""
    n = _worker_count(args.nproc_per_node)
    restarts = 0
    while True:
        procs = []
        for r in range(n):
            env = dict(os.environ,
                       PADDLE_TRAINER_ID=str(r),
                       PADDLE_TRAINERS_NUM=str(n),
                       PADDLE_LOCAL_RANK=str(r),
                       JAX_PLATFORMS=args.backend or "cpu",
                       PADDLE_LAUNCH_MODE="simulation")
            if args.master:
                # real multi-process rendezvous: workers' init_parallel_env
                # dials jax.distributed.initialize at this address
                # (reference: launch/main.py sets PADDLE_MASTER for the pod)
                env["PADDLE_MASTER"] = args.master
            out = None
            if args.log_dir:
                os.makedirs(args.log_dir, exist_ok=True)
                out = open(os.path.join(args.log_dir, f"worker.{r}.log"), "w")
            procs.append((subprocess.Popen(
                [sys.executable, args.script] + list(args.script_args),
                env=env, stdout=out, stderr=subprocess.STDOUT if out else None),
                out))
        # poll all workers; on first failure kill the rest of the group (a
        # crashed rank leaves peers blocked in rendezvous forever otherwise —
        # reference behavior: pod terminates on first worker failure)
        codes = [None] * len(procs)
        while any(c is None for c in codes):
            for i, (p, _) in enumerate(procs):
                if codes[i] is None:
                    codes[i] = p.poll()
            if any(c not in (None, 0) for c in codes):
                for i, (p, _) in enumerate(procs):
                    if codes[i] is None:
                        p.terminate()
                for i, (p, _) in enumerate(procs):
                    if codes[i] is None:
                        try:
                            codes[i] = p.wait(timeout=10)
                        except subprocess.TimeoutExpired:
                            p.kill()
                            codes[i] = p.wait()
                break
            time.sleep(0.2)
        for _, out in procs:
            if out:
                out.close()
        if all(c == 0 for c in codes):
            return 0
        restarts += 1
        if restarts > args.max_restart:
            sys.stderr.write(
                f"launch: workers failed (codes {codes}), max_restart "
                f"({args.max_restart}) exhausted\n")
            return 1
        try:  # recovery telemetry; the restart itself must never fail on it
            from ...observability.catalog import metric
            metric("elastic_pod_restarts_total").inc()
        except Exception:  # noqa: BLE001
            pass
        sys.stderr.write(
            f"launch: workers failed (codes {codes}), restart "
            f"{restarts}/{args.max_restart}\n")
        time.sleep(1.0)


def main(argv=None):
    args = _parse(argv if argv is not None else sys.argv[1:])
    if args.nproc_per_node is not None and _worker_count(args.nproc_per_node) > 1:
        sys.exit(_run_local_procs(args))
    # single process per host: bootstrap jax.distributed then exec the script
    if args.backend:
        import jax
        jax.config.update("jax_platforms", args.backend)
    lo, hi = _nnodes_range(args.nnodes)
    if args.master and lo > 1:
        os.environ.setdefault("PADDLE_MASTER", args.master)
        os.environ.setdefault("PADDLE_TRAINERS_NUM", str(lo))
        os.environ.setdefault("PADDLE_TRAINER_ID", str(args.rank))
    from ..parallel_env import init_parallel_env
    init_parallel_env()
    sys.argv = [args.script] + list(args.script_args)
    runpy.run_path(args.script, run_name="__main__")


def launch():
    main()


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference: python/paddle/distributed/spawn.py. Single-controller JAX
    drives all local chips from one process, so spawn degenerates to a
    direct call (the mesh provides the parallelism)."""
    func(*args)
    return None
