"""DTensor placements + ProcessMesh → jax.sharding.

reference: paddle/phi/core/distributed/auto_parallel/placement_types.h
(Shard/Replicate/Partial), process_mesh.h, dist_attr.h;
python/paddle/distributed/auto_parallel/process_mesh.py.

Mapping: ProcessMesh ≡ jax.sharding.Mesh; placements list (one per mesh dim)
≡ PartitionSpec derived by inverting "placement per mesh-axis" into
"mesh-axis per tensor-dim"; Partial ≡ unreduced values (we materialize them
eagerly by psum when leaving shard_map regions — GSPMD tracks them
internally otherwise).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["Placement", "Shard", "Replicate", "Partial", "ProcessMesh",
           "to_partition_spec", "build_mesh"]

_default_mesh = [None]


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim):
        self.dim = dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")


class Partial(Placement):
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))


class ProcessMesh:
    """reference: python/paddle/distributed/auto_parallel/process_mesh.py.
    Wraps a jax Mesh; process ids map to device ids (single-controller)."""

    def __init__(self, mesh=None, dim_names=None, shape=None):
        if isinstance(mesh, Mesh):
            self._jax_mesh = mesh
            self._shape = tuple(mesh.devices.shape)
            self._dim_names = list(mesh.axis_names)
            return
        if mesh is None and shape is not None:
            arr = np.arange(int(np.prod(shape))).reshape(shape)
        else:
            arr = np.asarray(mesh)
        self._shape = tuple(arr.shape)
        self._dim_names = list(dim_names) if dim_names else [
            f"d{i}" for i in range(arr.ndim)]
        devices = np.asarray(jax.devices())
        flat = arr.reshape(-1)
        if flat.max() >= devices.size:
            # virtual mesh larger than device count: tile devices (useful for
            # single-chip dry runs; real runs require enough devices)
            dev_arr = devices[flat % devices.size].reshape(self._shape)
        else:
            dev_arr = devices[flat].reshape(self._shape)
        self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))

    @property
    def mesh(self):
        return self._jax_mesh

    @property
    def jax_mesh(self):
        return self._jax_mesh

    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return list(range(int(np.prod(self._shape))))

    def get_dim_size(self, name):
        return self._shape[self._dim_names.index(name)]

    def get_mesh_with_dim(self, name, index=None):
        i = self._dim_names.index(name)
        if index is None:
            # reorder with `name` first
            order = [i] + [j for j in range(self.ndim) if j != i]
            arr = np.transpose(np.asarray(self._jax_mesh.devices), order)
            names = [self._dim_names[j] for j in order]
            return ProcessMesh(Mesh(arr, tuple(names)))
        arr = np.take(np.asarray(self._jax_mesh.devices), index, axis=i)
        names = [n for j, n in enumerate(self._dim_names) if j != i]
        return ProcessMesh(Mesh(arr, tuple(names)))

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and self._shape == other._shape
                and self._dim_names == other._dim_names)

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, dim_names={self._dim_names})"

    def __enter__(self):
        _default_mesh[0] = self
        return self

    def __exit__(self, *exc):
        _default_mesh[0] = None
        return False


def build_mesh(shape, dim_names):
    return ProcessMesh(shape=shape, dim_names=dim_names)


def to_partition_spec(placements, ndim=None):
    """Invert per-mesh-axis placements into a per-tensor-dim PartitionSpec.

    placements[i] describes mesh axis i (paddle convention). A tensor dim may
    be sharded over multiple mesh axes (they stack in order)."""
    dim_to_axes: dict[int, list] = {}
    for axis_idx, p in enumerate(placements):
        if isinstance(p, Shard):
            dim_to_axes.setdefault(p.dim, []).append(axis_idx)
    max_dim = (max(dim_to_axes) + 1) if dim_to_axes else 0
    n = ndim if ndim is not None else max_dim
    spec = []
    for d in range(n):
        axes = dim_to_axes.get(d)
        if not axes:
            spec.append(None)
        elif len(axes) == 1:
            spec.append(axes[0])
        else:
            spec.append(tuple(axes))
    return spec


def named_sharding(mesh: ProcessMesh, placements, ndim):
    spec = to_partition_spec(placements, ndim)
    names = mesh.dim_names
    resolved = []
    for s in spec:
        if s is None:
            resolved.append(None)
        elif isinstance(s, tuple):
            resolved.append(tuple(names[i] for i in s))
        else:
            resolved.append(names[s])
    return NamedSharding(mesh.jax_mesh, PartitionSpec(*resolved))
