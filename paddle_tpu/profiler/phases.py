"""Per-phase engine wall-time accountant over a CLOSED phase registry.

"Where did the milliseconds go": the serving engine calls
``begin_step()`` at the top of each ``step()``, ``mark(phase)`` at every
phase boundary it crosses, and ``end_step()`` at the bottom. Each mark
attributes the wall time since the previous mark to one registered
phase, so the phases PARTITION the step — attribution coverage
(attributed / measured wall) is structural, not sampled, and the
harness asserts it stays >= 95%.

Catalog discipline (same as FAULT_SITES / EVENT_KINDS): ``PHASES`` is
the closed set; marking an unknown phase raises, ``tools/static_check.py``
pins every phase literal in ``profiler/`` and ``serving.py`` to this
dict, and OBSERVABILITY.md documents each row (both directions).

Disabled-mode contract (same as the flight recorder): every mutation
starts with one attribute check and returns before allocating, so a
disabled accountant costs one branch per call site. Call sites that
would build kwargs guard with ``if acct.enabled:`` themselves.
"""

from __future__ import annotations

import os
import threading
import time

__all__ = ["PHASES", "PhaseAccountant", "get_phase_accountant"]

# The closed set of engine phases. One row per phase in
# OBSERVABILITY.md's phase registry; serving.py may mark() only these.
PHASES = {
    "admit": "deadline sweep + queue admission: lane assignment and "
             "paged-KV reservation for queued requests",
    "prefill.chunk": "one chunked-prefill program call (warm path), "
                     "including argument staging",
    "decode.dispatch": "building lane operands and launching one fused "
                       "K-step decode tile (async dispatch)",
    "decode.readback": "drained-tile bookkeeping around the host sync: "
                       "retire checks, trace emission, tile accounting",
    "hostsync": "host blocked on device->host readback of a decode "
                "token tile (the np.asarray wait)",
    "lane_upload": "rebuilding + uploading device lane state after a "
                   "membership change (admit/retire/shed)",
    "commit": "crediting sampled tokens to streams: emit callbacks, "
              "EOS/length finish checks",
    "compile": "cold-path program construction: pir_jit build + first "
               "trace/compile of a decode or prefill variant",
}


class PhaseAccountant:
    """Mark-based timeline splitter: consecutive ``mark()`` calls split
    the step's wall clock into phase-attributed segments."""

    __slots__ = ("enabled", "_lock", "_t_step", "_last", "_wall", "_attr",
                 "_phase_s", "_phase_n", "_tenant_s", "_steps", "_hist",
                 "_cov")

    def __init__(self, enabled=False):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._hist = None       # phase -> bound catalog histogram child
        self._cov = None        # bound coverage gauge
        self._zero()

    def _zero(self):
        self._t_step = None     # perf_counter at begin_step
        self._last = None       # perf_counter at the previous mark
        self._wall = 0.0        # sum of measured step wall time
        self._attr = 0.0        # sum of phase-attributed time
        self._phase_s = {p: 0.0 for p in PHASES}
        self._phase_n = {p: 0 for p in PHASES}
        self._tenant_s = {}     # tenant -> attributed seconds
        self._steps = 0

    def _bind(self):
        # lazy so a disabled accountant never imports the catalog
        from ..observability.catalog import metric
        self._hist = {p: metric("serving_phase_seconds", phase=p)
                      for p in PHASES}
        self._cov = metric("serving_phase_coverage_ratio")

    # -- lifecycle -----------------------------------------------------------
    def enable(self):
        self.enabled = True

    def disable(self):
        self.enabled = False

    def reset(self):
        with self._lock:
            self._zero()

    # -- accounting ----------------------------------------------------------
    def begin_step(self):
        if not self.enabled:
            return
        self._t_step = self._last = time.perf_counter()

    def mark(self, phase, tenant=None, dt=None):
        """Attribute wall time since the previous mark (or ``dt`` seconds
        carved out of the current segment) to `phase`; unknown phases
        raise (closed registry). `tenant` additionally credits the
        in-memory per-tenant split."""
        if not self.enabled:
            return
        if phase not in PHASES:
            raise KeyError(f"unknown profiler phase {phase!r}; registered "
                           f"phases: {sorted(PHASES)}")
        if self._last is None:      # mark outside begin_step: ignore
            return
        if self._hist is None:
            self._bind()
        now = time.perf_counter()
        seg = now - self._last if dt is None else min(dt, now - self._last)
        self._last = now
        with self._lock:
            self._attr += seg
            self._phase_s[phase] += seg
            self._phase_n[phase] += 1
            if tenant is not None:
                self._tenant_s[tenant] = self._tenant_s.get(tenant, 0.0) + seg
        self._hist[phase].observe(seg)

    def end_step(self):
        if not self.enabled:
            return
        if self._t_step is None:
            return
        now = time.perf_counter()
        with self._lock:
            self._wall += now - self._t_step
            self._steps += 1
            cov = self._attr / self._wall if self._wall > 0 else 0.0
        self._t_step = self._last = None
        if self._cov is not None:
            self._cov.set(cov)

    def credit_tenants(self, tenants, seconds):
        """Split `seconds` of already-attributed shared time (one decode
        tile serves many lanes) evenly across `tenants` for the
        per-tenant report."""
        if not self.enabled:
            return
        if not tenants:
            return
        share = seconds / len(tenants)
        with self._lock:
            for t in tenants:
                self._tenant_s[t] = self._tenant_s.get(t, 0.0) + share

    # -- reporting -----------------------------------------------------------
    @property
    def coverage(self):
        with self._lock:
            return self._attr / self._wall if self._wall > 0 else 0.0

    def report(self):
        """Machine-readable accounting: measured wall, attributed time,
        coverage ratio, per-phase seconds/counts, per-tenant seconds."""
        with self._lock:
            return {
                "steps": self._steps,
                "wall_s": self._wall,
                "attributed_s": self._attr,
                "coverage": (self._attr / self._wall
                             if self._wall > 0 else 0.0),
                "phases": {p: {"seconds": self._phase_s[p],
                               "marks": self._phase_n[p]}
                           for p in PHASES if self._phase_n[p]},
                "tenants": dict(sorted(self._tenant_s.items())),
            }


_default_accountant: PhaseAccountant | None = None
_default_lock = threading.Lock()


def get_phase_accountant() -> PhaseAccountant:
    """Process-wide accountant (recorder idiom): disabled unless
    FLAGS_observability is truthy in the env; tests and the loadgen
    harness enable()/reset() it explicitly."""
    global _default_accountant
    if _default_accountant is None:
        with _default_lock:
            if _default_accountant is None:
                _default_accountant = PhaseAccountant(
                    enabled=os.environ.get("FLAGS_observability", "")
                    .lower() in ("1", "true", "yes", "on"))
    return _default_accountant
