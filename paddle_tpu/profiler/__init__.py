"""Profiler. reference: python/paddle/profiler/ (profiler.py:358 Profiler,
ProfilerState:89, RecordEvent in utils.py, statistics in
profiler_statistic.py, timer.py throughput benchmark).

TPU-native: device tracing is jax.profiler (XPlane -> TensorBoard trace
viewer), replacing the CUPTI tracer stack
(paddle/fluid/platform/profiler/cuda_tracer.cc). Host-side annotated ranges
use jax.profiler.TraceAnnotation so they interleave with XLA's device events
in the same trace; a lightweight host-event table backs summary().
"""

from __future__ import annotations

import enum
import os
import time

import jax

from ..observability.tracing import get_tracer as _host_tracer
from .phases import PHASES, PhaseAccountant, get_phase_accountant

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "SortedKeys", "SummaryView", "benchmark",
           "PHASES", "PhaseAccountant", "get_phase_accountant"]


class ProfilerState(enum.Enum):
    """reference: profiler/profiler.py:89."""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class SortedKeys(enum.Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(enum.Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


class RecordEvent:
    """Annotated host range, visible in the device trace AND recorded as a
    span in the observability tracer (observability/tracing.py) — so
    summary() aggregates it and export_chrome_tracing's host trace shows
    it with parent/child nesting.
    reference: python/paddle/profiler/utils.py RecordEvent +
    C++ paddle/fluid/platform/profiler/event_tracing.h."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = None
        self._span = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        # ungated tracer path: profiler users asked for recording
        # explicitly, independent of the global observability flag
        self._span = _host_tracer().begin(self.name)

    def end(self):
        if self._ann is not None:
            _host_tracer().end(self._span)
            self._span = None
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """reference: profiler/profiler.py make_scheduler — step-state machine."""
    cycle = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


class _ChromeTracingHandler:
    """on_trace_ready callback carrying the target dir; the Profiler reads
    .log_dir at construction so jax writes the device trace there
    directly, and on trace-ready this handler exports the HOST spans
    (RecordEvent + observability spans) as a chrome-trace JSON alongside
    it — RecordEvent ranges actually appear in the exported artifact."""

    def __init__(self, dir_name, worker_name=None):
        self.log_dir = dir_name
        self.worker_name = worker_name
        self.last_host_trace = None
        os.makedirs(dir_name, exist_ok=True)

    def __call__(self, prof):
        # device trace already written into self.log_dir by stop_trace;
        # add the host-span trace (marker-scoped to this profiler run)
        marker = getattr(prof, "_trace_marker", 0)
        name = (f"host_trace.{self.worker_name}.json" if self.worker_name
                else f"host_trace.{os.getpid()}.json")
        self.last_host_trace = _host_tracer().export_chrome_trace(
            os.path.join(self.log_dir, name), marker)


def export_chrome_tracing(dir_name, worker_name=None):
    """Trace lands in dir_name (TensorBoard-loadable; chrome://tracing
    reads the contained .trace.json.gz plus the host_trace.*.json with
    the RecordEvent span tree)."""
    return _ChromeTracingHandler(dir_name, worker_name)


def load_profiler_result(path):
    raise NotImplementedError(
        "load the trace directory in TensorBoard (jax XPlane format)")


class Profiler:
    """reference: python/paddle/profiler/profiler.py:358.

    with Profiler(targets=[...], scheduler=(2, 5)) as p:
        for batch in loader:
            train_step(batch)
            p.step()
    """

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        self._timer_only = timer_only
        self._log_dir = (getattr(on_trace_ready, "log_dir", None)
                         or os.environ.get("PADDLE_PROFILER_LOGDIR",
                                           "/tmp/paddle_tpu_profile"))
        if callable(scheduler):
            self._scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(closed=max(start, 0), ready=0,
                                             record=end - start, repeat=1)
        else:
            self._scheduler = None  # always record
        self._on_trace_ready = on_trace_ready
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._tracing = False
        self._timer = benchmark()

    # -- state machine ------------------------------------------------------
    def _target_state(self):
        if self._scheduler is None:
            return ProfilerState.RECORD
        return self._scheduler(self._step)

    def _sync(self):
        want = self._target_state()
        recording = want in (ProfilerState.RECORD,
                             ProfilerState.RECORD_AND_RETURN)
        if recording and not self._tracing and not self._timer_only:
            jax.profiler.start_trace(self._log_dir)
            self._tracing = True
        if not recording and self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            if self._on_trace_ready:
                self._on_trace_ready(self)
        self._state = want

    def start(self):
        # tracer watermark: summary()/host trace report only spans
        # recorded during THIS profiler run
        self._trace_marker = _host_tracer().marker()
        self._timer.begin()
        self._sync()
        return self

    def stop(self):
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            if self._on_trace_ready:
                self._on_trace_ready(self)
        self._state = ProfilerState.CLOSED

    def step(self, num_samples=None):
        self._timer.step(num_samples)
        self._step += 1
        self._sync()

    def step_info(self, unit="samples"):
        return self._timer.step_info(unit)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- reporting ----------------------------------------------------------
    def summary(self, sorted_by=SortedKeys.CPUTotal, op_detail=True,
                thread_sep=False, time_unit="ms"):
        """Host-event summary table, aggregated from the observability
        tracer's spans (device kernels live in the exported trace;
        reference: profiler_statistic.py)."""
        unit = {"s": 1.0, "ms": 1e3, "us": 1e6}[time_unit]
        marker = getattr(self, "_trace_marker", 0)
        rows = [(name, len(ds), sum(ds) * unit,
                 sum(ds) / len(ds) * unit, max(ds) * unit, min(ds) * unit)
                for name, ds in
                _host_tracer().durations_by_name(marker).items() if ds]
        rows.sort(key=lambda r: -r[2])
        header = (f"{'Name':<40}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
                  f"{'Avg':>12}{'Max':>12}{'Min':>12}")
        lines = [header, "-" * len(header)]
        for r in rows:
            lines.append(f"{r[0]:<40}{r[1]:>8}{r[2]:>14.3f}{r[3]:>12.3f}"
                         f"{r[4]:>12.3f}{r[5]:>12.3f}")
        table = "\n".join(lines)
        print(table)
        return table


class benchmark:
    """Throughput timer. reference: python/paddle/profiler/timer.py
    (Benchmark: ips / step cost, `paddle.profiler.benchmark()`)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._t0 = None
        self._last = None
        self._steps = 0
        self._samples = 0
        self._durs = []

    def begin(self):
        self._t0 = self._last = time.perf_counter()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            self._durs.append(now - self._last)
        self._last = now
        self._steps += 1
        if num_samples:
            self._samples += num_samples

    def step_info(self, unit="samples"):
        if not self._durs:
            return "no steps recorded"
        import numpy as np
        durs = np.asarray(self._durs[max(0, len(self._durs) - 100):])
        avg = durs.mean()
        ips = (self._samples / self._steps / avg) if self._samples else 1.0 / avg
        return (f"avg step: {avg * 1e3:.2f} ms, ips: {ips:.2f} {unit}/s "
                f"(last {len(durs)} steps)")

    def end(self):
        pass


def export_protobuf(profiler_result=None, path="profile.pb"):
    """reference: profiler.export_protobuf — the TPU-native trace artifact
    is the chrome-trace/tensorboard dump jax.profiler writes; this exports
    the collected host events as a length-prefixed binary record file."""
    import pickle
    if profiler_result is None:
        raise ValueError(
            "export_protobuf needs a profiler result (e.g. a Profiler's "
            "collected events); got None")
    events = getattr(profiler_result, "events", None)
    if events is None:
        events = profiler_result
    if callable(events):
        events = events()
    # strip unpicklable members (scheduler closures etc.): keep plain data
    try:
        data = pickle.dumps(events, protocol=4)
    except Exception:
        data = pickle.dumps(repr(events), protocol=4)
    with open(path, "wb") as f:
        f.write(len(data).to_bytes(8, "little"))
        f.write(data)
    return path
