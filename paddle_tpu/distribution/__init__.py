"""paddle.distribution — probability distributions, transforms, KL.

TPU-native counterpart of python/paddle/distribution/ (reference package
``__init__.py`` exports the same names).
"""
from .distribution import Distribution, ExponentialFamily  # noqa: F401
from .continuous import (  # noqa: F401
    Normal, Uniform, Beta, Gamma, Exponential, Cauchy, Chi2, Gumbel,
    Laplace, LogNormal, StudentT, ContinuousBernoulli,
)
from .discrete import (  # noqa: F401
    Bernoulli, Binomial, Categorical, Geometric, Multinomial, Poisson,
)
from .multivariate import Dirichlet, MultivariateNormal, LKJCholesky  # noqa: F401
from .transform import (  # noqa: F401
    Transform, AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform,
    TransformedDistribution, Independent,
)
from .kl import kl_divergence, register_kl  # noqa: F401

__all__ = [
    "Distribution", "ExponentialFamily",
    "Normal", "Uniform", "Beta", "Gamma", "Exponential", "Cauchy", "Chi2",
    "Gumbel", "Laplace", "LogNormal", "StudentT", "ContinuousBernoulli",
    "Bernoulli", "Binomial", "Categorical", "Geometric", "Multinomial",
    "Poisson", "Dirichlet", "MultivariateNormal", "LKJCholesky",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
    "TransformedDistribution", "Independent",
    "kl_divergence", "register_kl",
]
