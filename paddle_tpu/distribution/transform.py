"""Bijective transforms + TransformedDistribution + Independent.

Reference: python/paddle/distribution/transform.py (Transform taxonomy with
Type.BIJECTION etc.), transformed_distribution.py, independent.py.
"""
from __future__ import annotations

import enum
import math

import jax.numpy as jnp
from jax.scipy import special as jsp

from .distribution import Distribution, _arr, _wrap, _shape

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
    "TransformedDistribution", "Independent",
]


class Type(enum.Enum):
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"


class Transform:
    _type = Type.INJECTION

    @classmethod
    def _is_injective(cls):
        return cls._type in (Type.BIJECTION, Type.INJECTION)

    def __call__(self, x):
        if isinstance(x, Distribution):
            return TransformedDistribution(x, [self])
        return self.forward(x)

    def forward(self, x):
        return _wrap(self._forward(_arr(x)))

    def inverse(self, y):
        return _wrap(self._inverse(_arr(y)))

    def forward_log_det_jacobian(self, x):
        return _wrap(self._forward_log_det_jacobian(_arr(x)))

    def inverse_log_det_jacobian(self, y):
        y = _arr(y)
        return _wrap(-self._forward_log_det_jacobian(self._inverse(y)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # event dimensions consumed/produced (domain/codomain event rank)
    _domain_event_rank = 0
    _codomain_event_rank = 0


class AbsTransform(Transform):
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # principal branch

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = _arr(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return 1 / (1 + jnp.exp(-x))

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jnp.logaddexp(jnp.zeros_like(x), -x) - jnp.logaddexp(jnp.zeros_like(x), x)


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        return 2.0 * (math.log(2.0) - x - jnp.logaddexp(jnp.zeros_like(x), -2.0 * x))


class SoftmaxTransform(Transform):
    _type = Type.OTHER
    _domain_event_rank = 1
    _codomain_event_rank = 1

    def _forward(self, x):
        x = x - jnp.max(x, -1, keepdims=True)
        e = jnp.exp(x)
        return e / jnp.sum(e, -1, keepdims=True)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError("SoftmaxTransform is not injective")


class StickBreakingTransform(Transform):
    _type = Type.BIJECTION
    _domain_event_rank = 1
    _codomain_event_rank = 1

    def _forward(self, x):
        offset = x.shape[-1] - jnp.cumsum(jnp.ones_like(x), -1) + 1
        z = 1 / (1 + jnp.exp(-(x - jnp.log(offset))))
        zc = jnp.cumprod(1 - z, -1)
        pad = jnp.ones(x.shape[:-1] + (1,), x.dtype)
        return jnp.concatenate([z, pad], -1) * jnp.concatenate([pad, zc], -1)

    def _inverse(self, y):
        y_crop = y[..., :-1]
        offset = y_crop.shape[-1] - jnp.cumsum(jnp.ones_like(y_crop), -1) + 1
        sf = 1 - jnp.cumsum(y_crop, -1)
        x = jnp.log(y_crop / jnp.clip(sf, 1e-12)) + jnp.log(offset)
        return x

    def _forward_log_det_jacobian(self, x):
        # identity: log|detJ| = sum_i(-x'_i + logsigmoid(x'_i) + log(y_i)),
        # x' = x - log(offset)
        y = self._forward(x)
        offset = x.shape[-1] - jnp.cumsum(jnp.ones_like(x), -1) + 1
        xs = x - jnp.log(offset)
        logsig = -jnp.logaddexp(jnp.zeros_like(xs), -xs)
        return jnp.sum(-xs + logsig + jnp.log(jnp.clip(y[..., :-1], 1e-38)), -1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        if int(jnp.prod(jnp.asarray(self.in_event_shape or (1,)))) != \
           int(jnp.prod(jnp.asarray(self.out_event_shape or (1,)))):
            raise ValueError("in/out event sizes must match")
        self._domain_event_rank = len(self.in_event_shape)
        self._codomain_event_rank = len(self.out_event_shape)

    def _forward(self, x):
        n = len(self.in_event_shape)
        batch = x.shape[:x.ndim - n] if n else x.shape
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        n = len(self.out_event_shape)
        batch = y.shape[:y.ndim - n] if n else y.shape
        return y.reshape(batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        n = len(self.in_event_shape)
        batch = x.shape[:x.ndim - n] if n else x.shape
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:len(shape) - n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:len(shape) - n]) + self.in_event_shape


class IndependentTransform(Transform):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        self._domain_event_rank = base._domain_event_rank + self.reinterpreted_batch_rank
        self._codomain_event_rank = base._codomain_event_rank + self.reinterpreted_batch_rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ld = self.base._forward_log_det_jacobian(x)
        return jnp.sum(ld, axis=tuple(range(-self.reinterpreted_batch_rank, 0)))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._domain_event_rank = max(
            [t._domain_event_rank for t in self.transforms] or [0])
        self._codomain_event_rank = max(
            [t._codomain_event_rank for t in self.transforms] or [0])

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._forward_log_det_jacobian(x)
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape


class StackTransform(Transform):
    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _unstack(self, x):
        return [jnp.squeeze(s, self.axis) for s in
                jnp.split(x, len(self.transforms), self.axis)]

    def _forward(self, x):
        parts = [t._forward(p) for t, p in zip(self.transforms, self._unstack(x))]
        return jnp.stack(parts, self.axis)

    def _inverse(self, y):
        parts = [t._inverse(p) for t, p in zip(self.transforms, self._unstack(y))]
        return jnp.stack(parts, self.axis)

    def _forward_log_det_jacobian(self, x):
        parts = [t._forward_log_det_jacobian(p)
                 for t, p in zip(self.transforms, self._unstack(x))]
        return jnp.stack(parts, self.axis)


class Independent(Distribution):
    """Reinterpret trailing batch dims of ``base`` as event dims.

    Reference: python/paddle/distribution/independent.py.
    """

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        if self.reinterpreted_batch_rank > len(base.batch_shape):
            raise ValueError("reinterpreted_batch_rank exceeds base batch rank")
        cut = len(base.batch_shape) - self.reinterpreted_batch_rank
        super().__init__(base.batch_shape[:cut],
                         base.batch_shape[cut:] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)._data
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return _wrap(jnp.sum(lp, axis=axes) if axes else lp)

    def entropy(self):
        ent = self.base.entropy()._data
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return _wrap(jnp.sum(ent, axis=axes) if axes else ent)


class TransformedDistribution(Distribution):
    """Distribution of T(X) for X ~ base and a chain of transforms T.

    Reference: python/paddle/distribution/transformed_distribution.py:26.
    """

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        chain = ChainTransform(self.transforms)
        base_event = base.event_shape
        shape = chain.forward_shape(base.batch_shape + base.event_shape)
        # event rank grows to at least the chain's codomain event rank
        event_rank = max(len(base_event), chain._codomain_event_rank)
        cut = len(shape) - event_rank
        super().__init__(shape[:cut], shape[cut:])
        self._chain = chain

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return _wrap(self._chain._forward(_arr(x)))

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return _wrap(self._chain._forward(_arr(x)))

    def log_prob(self, value):
        y = _arr(value)
        x = self._chain._inverse(y)
        ld = self._chain._forward_log_det_jacobian(x)
        base_lp = self.base.log_prob(_wrap(x))._data
        # sum base log-prob over dims that became event dims
        extra = len(self.event_shape) - len(self.base.event_shape) \
            - (self._chain._codomain_event_rank - self._chain._domain_event_rank)
        if extra > 0:
            base_lp = jnp.sum(base_lp, axis=tuple(range(-extra, 0)))
        # reduce jacobian over event dims beyond its natural rank
        jac_extra = len(self.event_shape) - self._chain._codomain_event_rank
        if jac_extra > 0 and jnp.ndim(ld) >= jac_extra:
            ld = jnp.sum(ld, axis=tuple(range(-jac_extra, 0)))
        return _wrap(base_lp - ld)
