"""Multivariate distributions: Dirichlet, MultivariateNormal, LKJCholesky.

Reference: python/paddle/distribution/{dirichlet,multivariate_normal,
lkj_cholesky}.py — rebuilt on jax.random / jax.scipy.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
from jax import random as jrandom
from jax.scipy import special as jsp

from .distribution import Distribution, ExponentialFamily, _arr, _wrap, _shape

__all__ = ["Dirichlet", "MultivariateNormal", "LKJCholesky"]


class Dirichlet(ExponentialFamily):
    """Dirichlet(concentration). Reference: python/paddle/distribution/dirichlet.py:25."""

    def __init__(self, concentration, name=None):
        self.concentration = _arr(concentration)
        if self.concentration.ndim < 1:
            raise ValueError("concentration must be at least 1-dimensional")
        super().__init__(self.concentration.shape[:-1], self.concentration.shape[-1:])

    @property
    def mean(self):
        return _wrap(self.concentration /
                     jnp.sum(self.concentration, -1, keepdims=True))

    @property
    def variance(self):
        a0 = jnp.sum(self.concentration, -1, keepdims=True)
        m = self.concentration / a0
        return _wrap(m * (1 - m) / (a0 + 1))

    def rsample(self, shape=()):
        out = jrandom.dirichlet(self._key(), self.concentration,
                                _shape(shape) + self.batch_shape)
        return _wrap(out)

    def sample(self, shape=()):
        return self.rsample(shape)

    def log_prob(self, value):
        v = _arr(value)
        a = self.concentration
        return _wrap(jnp.sum((a - 1) * jnp.log(v), -1)
                     + jsp.gammaln(jnp.sum(a, -1))
                     - jnp.sum(jsp.gammaln(a), -1))

    def entropy(self):
        a = self.concentration
        a0 = jnp.sum(a, -1)
        k = a.shape[-1]
        lnB = jnp.sum(jsp.gammaln(a), -1) - jsp.gammaln(a0)
        return _wrap(lnB + (a0 - k) * jsp.digamma(a0)
                     - jnp.sum((a - 1) * jsp.digamma(a), -1))

    def kl_divergence(self, other):
        if isinstance(other, Dirichlet):
            a, b = self.concentration, other.concentration
            a0 = jnp.sum(a, -1)
            return _wrap(jsp.gammaln(a0) - jnp.sum(jsp.gammaln(a), -1)
                         - jsp.gammaln(jnp.sum(b, -1)) + jnp.sum(jsp.gammaln(b), -1)
                         + jnp.sum((a - b) * (jsp.digamma(a) - jsp.digamma(a0)[..., None]), -1))
        return super().kl_divergence(other)


class MultivariateNormal(Distribution):
    """MultivariateNormal(loc, covariance_matrix | precision_matrix | scale_tril).

    Reference: python/paddle/distribution/multivariate_normal.py.
    """

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _arr(loc)
        given = sum(x is not None for x in
                    (covariance_matrix, precision_matrix, scale_tril))
        if given != 1:
            raise ValueError("Exactly one of covariance_matrix, "
                             "precision_matrix, scale_tril must be given")
        if scale_tril is not None:
            self._scale_tril = _arr(scale_tril)
        elif covariance_matrix is not None:
            self._scale_tril = jnp.linalg.cholesky(_arr(covariance_matrix))
        else:
            prec = _arr(precision_matrix)
            self._scale_tril = jnp.linalg.cholesky(jnp.linalg.inv(prec))
        d = self.loc.shape[-1]
        batch = jnp.broadcast_shapes(self.loc.shape[:-1], self._scale_tril.shape[:-2])
        super().__init__(batch, (d,))

    @property
    def scale_tril(self):
        return _wrap(self._scale_tril)

    @property
    def covariance_matrix(self):
        L = self._scale_tril
        return _wrap(L @ jnp.swapaxes(L, -1, -2))

    @property
    def precision_matrix(self):
        return _wrap(jnp.linalg.inv(self.covariance_matrix._data))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape + self.event_shape))

    @property
    def variance(self):
        var = jnp.sum(self._scale_tril ** 2, -1)
        return _wrap(jnp.broadcast_to(var, self.batch_shape + self.event_shape))

    def rsample(self, shape=()):
        full = self._extend_shape(shape)
        eps = jrandom.normal(self._key(), full, self.loc.dtype)
        return _wrap(self.loc + jnp.einsum("...ij,...j->...i", self._scale_tril, eps))

    def sample(self, shape=()):
        return self.rsample(shape)

    def log_prob(self, value):
        v = _arr(value)
        d = self.event_shape[0]
        diff = v - self.loc
        # solve L y = diff  => y = L^-1 diff; M = |y|^2 is the Mahalanobis dist
        y = jnp.vectorize(
            lambda L, b: jnp.linalg.solve(L, b), signature="(d,d),(d)->(d)"
        )(jnp.broadcast_to(self._scale_tril, diff.shape[:-1] + (d, d)), diff)
        M = jnp.sum(y ** 2, -1)
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)), -1)
        return _wrap(-0.5 * (d * math.log(2 * math.pi) + M) - half_logdet)

    def entropy(self):
        d = self.event_shape[0]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)), -1)
        out = 0.5 * d * (1 + math.log(2 * math.pi)) + half_logdet
        return _wrap(jnp.broadcast_to(out, self.batch_shape))

    def kl_divergence(self, other):
        if isinstance(other, MultivariateNormal):
            d = self.event_shape[0]
            L1, L2 = self._scale_tril, other._scale_tril
            logdet = (jnp.sum(jnp.log(jnp.diagonal(L2, axis1=-2, axis2=-1)), -1)
                      - jnp.sum(jnp.log(jnp.diagonal(L1, axis1=-2, axis2=-1)), -1))
            # tr(S2^-1 S1) = |L2^-1 L1|_F^2
            A = jnp.linalg.solve(L2, L1)
            tr = jnp.sum(A ** 2, (-2, -1))
            diff = other.loc - self.loc
            y = jnp.vectorize(
                lambda L, b: jnp.linalg.solve(L, b), signature="(d,d),(d)->(d)"
            )(jnp.broadcast_to(L2, diff.shape[:-1] + (d, d)), diff)
            M = jnp.sum(y ** 2, -1)
            return _wrap(logdet + 0.5 * (tr + M - d))
        return super().kl_divergence(other)


class LKJCholesky(Distribution):
    """LKJ prior over Cholesky factors of correlation matrices.

    Reference: python/paddle/distribution/lkj_cholesky.py. Sampling uses the
    onion method; both "onion" and "cvine" kwargs are accepted.
    """

    def __init__(self, dim, concentration=1.0, sample_method="onion", name=None):
        self.dim = int(dim)
        self.concentration = _arr(concentration)
        self.sample_method = sample_method
        if sample_method not in ("onion", "cvine"):
            raise ValueError("sample_method must be 'onion' or 'cvine'")
        super().__init__(self.concentration.shape, (self.dim, self.dim))

    def sample(self, shape=()):
        # Onion method (LKJ 2009): build rows incrementally; row i direction
        # uniform on the sphere with radius^2 ~ Beta(i/2, eta + (d-i-1)/2).
        d = self.dim
        batch = _shape(shape) + self.batch_shape
        eta = jnp.broadcast_to(self.concentration, self.batch_shape)
        key = self._key()
        keys = jrandom.split(key, 2 * d + 1)
        L = jnp.zeros(batch + (d, d), jnp.float32).at[..., 0, 0].set(1.0)
        for i in range(1, d):
            b = eta + (d - i - 1) / 2.0
            y = jrandom.beta(keys[2 * i], i / 2.0, b, batch)  # squared radius
            u = jrandom.normal(keys[2 * i + 1], batch + (i,))
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            w = jnp.sqrt(y)[..., None] * u
            L = L.at[..., i, :i].set(w)
            L = L.at[..., i, i].set(jnp.sqrt(jnp.clip(1.0 - y, 1e-12)))
        return _wrap(L)

    def log_prob(self, value):
        L = _arr(value)
        d = self.dim
        eta = self.concentration
        diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
        orders = jnp.arange(2, d + 1, dtype=L.dtype)
        unnorm = jnp.sum((d - orders + 2 * eta[..., None] - 2) * jnp.log(diag), -1)
        return _wrap(unnorm - self._log_normalizer())

    def _log_normalizer(self):
        # log C(eta, d) for the Cholesky-parametrized LKJ density
        d = self.dim
        eta = self.concentration
        i = jnp.arange(1, d, dtype=jnp.float32)
        return jnp.sum(
            (i / 2.0) * math.log(math.pi)
            + jsp.gammaln(eta[..., None] + (d - 1 - i) / 2.0)
            - jsp.gammaln(eta[..., None] + (d - 1) / 2.0), -1)
