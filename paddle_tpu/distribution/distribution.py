"""Distribution base classes.

TPU-native re-design of the reference's probability-distribution package
(reference: python/paddle/distribution/distribution.py:40 ``Distribution``,
python/paddle/distribution/exponential_family.py:22 ``ExponentialFamily``).
Internally everything is jax.numpy; public methods accept/return framework
Tensors.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.random import next_key

__all__ = ["Distribution", "ExponentialFamily"]


def _arr(x, dtype=None):
    """Coerce Tensor / python scalar / ndarray to a jnp array."""
    if isinstance(x, Tensor):
        x = x._data
    a = jnp.asarray(x)
    if dtype is not None:
        a = a.astype(dtype)
    elif jnp.issubdtype(a.dtype, jnp.integer) or a.dtype == jnp.bool_:
        a = a.astype(jnp.float32)
    return a


def _wrap(x):
    return Tensor(x, stop_gradient=True)


def _shape(s):
    if s is None:
        return ()
    if isinstance(s, int):
        return (s,)
    return tuple(int(d) for d in s)


class Distribution:
    """Base class for probability distributions.

    Mirrors the surface of the reference base class: ``sample``/``rsample``
    prepend ``shape`` to ``batch_shape + event_shape``; ``prob`` defaults to
    ``exp(log_prob)``; ``kl_divergence`` dispatches through the registry in
    :mod:`paddle_tpu.distribution.kl`.
    """

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = _shape(batch_shape)
        self._event_shape = _shape(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        lp = self.log_prob(value)
        return _wrap(jnp.exp(lp._data if isinstance(lp, Tensor) else lp))

    def probs(self, value):  # legacy alias kept by the reference
        return self.prob(value)

    def entropy(self):
        raise NotImplementedError

    def cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)

    # -- helpers shared by subclasses ------------------------------------
    def _extend_shape(self, sample_shape):
        return _shape(sample_shape) + self.batch_shape + self.event_shape

    def _key(self):
        return next_key()

    def __repr__(self):
        return f"{type(self).__name__}(batch_shape={self.batch_shape}, event_shape={self.event_shape})"


class ExponentialFamily(Distribution):
    """Distributions in the natural exponential family.

    Provides the Bregman-divergence based ``entropy`` fallback used by the
    reference (python/paddle/distribution/exponential_family.py:42
    ``_entropy``): H = -<∇A(θ), θ> + A(θ) - E[h(x)] computed with autodiff
    on the log-normalizer.
    """

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        nparams = [jnp.asarray(p) for p in self._natural_parameters]

        def log_norm(*ps):
            return jnp.sum(self._log_normalizer(*ps))

        lg_normal = self._log_normalizer(*nparams)
        grads = jax.grad(log_norm, argnums=tuple(range(len(nparams))))(*nparams)
        ent = -self._mean_carrier_measure + lg_normal
        for p, g in zip(nparams, grads):
            ent = ent - p * g
        return _wrap(ent)
