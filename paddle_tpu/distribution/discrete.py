"""Discrete distributions.

Mirrors python/paddle/distribution/{bernoulli,binomial,categorical,geometric,
multinomial,poisson}.py, re-built on jax.random.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
from jax import random as jrandom
from jax.scipy import special as jsp

from .distribution import Distribution, ExponentialFamily, _arr, _wrap, _shape

__all__ = ["Bernoulli", "Binomial", "Categorical", "Geometric", "Multinomial",
           "Poisson"]


class Bernoulli(ExponentialFamily):
    """Bernoulli(probs). Reference: python/paddle/distribution/bernoulli.py:40."""

    def __init__(self, probs, name=None):
        self.probs = _arr(probs)
        self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        super().__init__(self.probs.shape, ())

    @property
    def mean(self):
        return _wrap(self.probs)

    @property
    def variance(self):
        return _wrap(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        out = jrandom.bernoulli(self._key(), self.probs, self._extend_shape(shape))
        return _wrap(out.astype(self.probs.dtype))

    def rsample(self, shape=(), temperature=1.0):
        # Gumbel-softmax style relaxed sample (reference rsample uses
        # temperature-controlled logistic relaxation).
        u = jrandom.uniform(self._key(), self._extend_shape(shape), self.probs.dtype,
                            minval=1e-6, maxval=1 - 1e-6)
        logistic = jnp.log(u) - jnp.log1p(-u)
        return _wrap((self.logits + logistic) / temperature)

    def log_prob(self, value):
        v = _arr(value)
        eps = 1e-7
        p = jnp.clip(self.probs, eps, 1 - eps)
        return _wrap(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        eps = 1e-7
        p = jnp.clip(self.probs, eps, 1 - eps)
        return _wrap(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))

    def cdf(self, value):
        v = _arr(value)
        return _wrap(jnp.where(v < 0, 0.0, jnp.where(v < 1, 1 - self.probs, 1.0)))

    @property
    def _natural_parameters(self):
        return (self.logits,)

    def _log_normalizer(self, n1):
        return jnp.logaddexp(jnp.zeros_like(n1), n1)

    def kl_divergence(self, other):
        if isinstance(other, Bernoulli):
            eps = 1e-7
            p = jnp.clip(self.probs, eps, 1 - eps)
            q = jnp.clip(other.probs, eps, 1 - eps)
            return _wrap(p * (jnp.log(p) - jnp.log(q)) +
                         (1 - p) * (jnp.log1p(-p) - jnp.log1p(-q)))
        return super().kl_divergence(other)


class Binomial(Distribution):
    """Binomial(total_count, probs). Reference: python/paddle/distribution/binomial.py."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = jnp.asarray(total_count)
        self.probs = _arr(probs)
        batch = jnp.broadcast_shapes(jnp.shape(self.total_count), self.probs.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.total_count * self.probs, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(
            self.total_count * self.probs * (1 - self.probs), self.batch_shape))

    def sample(self, shape=()):
        n = int(jnp.max(self.total_count))
        u = jrandom.uniform(self._key(), (n,) + self._extend_shape(shape), self.probs.dtype)
        idx = jnp.arange(n).reshape((n,) + (1,) * (u.ndim - 1))
        draws = (u < self.probs) & (idx < self.total_count)
        return _wrap(jnp.sum(draws, axis=0).astype(self.probs.dtype))

    def log_prob(self, value):
        v = _arr(value)
        n, p = self.total_count, jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        comb = jsp.gammaln(n + 1) - jsp.gammaln(v + 1) - jsp.gammaln(n - v + 1)
        return _wrap(comb + v * jnp.log(p) + (n - v) * jnp.log1p(-p))

    def entropy(self):
        n = int(jnp.max(self.total_count))
        ks = jnp.arange(n + 1, dtype=self.probs.dtype)
        ks = ks.reshape((n + 1,) + (1,) * len(self.batch_shape))
        lp = self.log_prob(_wrap(ks))._data
        valid = ks <= self.total_count
        return _wrap(-jnp.sum(jnp.where(valid, jnp.exp(lp) * lp, 0.0), axis=0))

    def kl_divergence(self, other):
        if isinstance(other, Binomial):
            p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
            q = jnp.clip(other.probs, 1e-7, 1 - 1e-7)
            return _wrap(self.total_count * (
                p * (jnp.log(p) - jnp.log(q)) + (1 - p) * (jnp.log1p(-p) - jnp.log1p(-q))))
        return super().kl_divergence(other)


class Categorical(Distribution):
    """Categorical(logits). NOTE: like the reference
    (python/paddle/distribution/categorical.py:30), the constructor takes
    *unnormalized log-probabilities* named ``logits``.
    """

    def __init__(self, logits, name=None):
        self.logits = _arr(logits)
        self._log_p = self.logits - jsp.logsumexp(self.logits, axis=-1, keepdims=True)
        super().__init__(self.logits.shape[:-1], ())
        self._num_events = self.logits.shape[-1]

    @property
    def probs_param(self):
        return jnp.exp(self._log_p)

    def sample(self, shape=()):
        full = _shape(shape) + self.batch_shape
        out = jrandom.categorical(self._key(), self._log_p, axis=-1, shape=full)
        return _wrap(out)

    def log_prob(self, value):
        v = _arr(value, dtype=jnp.int32)
        lp = jnp.take_along_axis(
            jnp.broadcast_to(self._log_p, v.shape + (self._num_events,)),
            v[..., None], axis=-1)[..., 0]
        return _wrap(lp)

    def probs(self, value):
        v = _arr(value, dtype=jnp.int32)
        p = jnp.take_along_axis(
            jnp.broadcast_to(self.probs_param, v.shape + (self._num_events,)),
            v[..., None], axis=-1)[..., 0]
        return _wrap(p)

    def entropy(self):
        p = self.probs_param
        return _wrap(-jnp.sum(p * self._log_p, axis=-1))

    def kl_divergence(self, other):
        if isinstance(other, Categorical):
            p = self.probs_param
            return _wrap(jnp.sum(p * (self._log_p - other._log_p), axis=-1))
        from .kl import kl_divergence
        return kl_divergence(self, other)


class Geometric(Distribution):
    """Geometric(probs) — number of failures before first success (support 0,1,...).

    Reference: python/paddle/distribution/geometric.py.
    """

    def __init__(self, probs, name=None):
        self.probs = _arr(probs)
        super().__init__(self.probs.shape, ())

    @property
    def mean(self):
        # failures-before-success support (0-based): E[X] = (1-p)/p
        return _wrap(1.0 / self.probs - 1.0)

    @property
    def variance(self):
        return _wrap((1 - self.probs) / self.probs ** 2)

    @property
    def stddev(self):
        return _wrap(jnp.sqrt((1 - self.probs) / self.probs ** 2))

    def sample(self, shape=()):
        u = jrandom.uniform(self._key(), self._extend_shape(shape), self.probs.dtype,
                            minval=1e-7, maxval=1.0)
        return _wrap(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        v = _arr(value)
        return _wrap(v * jnp.log1p(-self.probs) + jnp.log(self.probs))

    def entropy(self):
        p = self.probs
        return _wrap(-((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p)

    def cdf(self, value):
        v = _arr(value)
        return _wrap(1 - jnp.power(1 - self.probs, jnp.floor(v) + 1))

    def kl_divergence(self, other):
        if isinstance(other, Geometric):
            p, q = self.probs, other.probs
            return _wrap(jnp.log(p / q) + (1 - p) / p * jnp.log((1 - p) / (1 - q)))
        return super().kl_divergence(other)


class Multinomial(Distribution):
    """Multinomial(total_count, probs). Reference: python/paddle/distribution/multinomial.py."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _arr(probs)
        self.probs = self.probs / jnp.sum(self.probs, axis=-1, keepdims=True)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return _wrap(self.total_count * self.probs)

    @property
    def variance(self):
        return _wrap(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        k = self.probs.shape[-1]
        full = _shape(shape) + self.batch_shape
        draws = jrandom.categorical(
            self._key(), jnp.log(self.probs), axis=-1,
            shape=(self.total_count,) + full)
        onehot = jnp.sum(jnp.eye(k, dtype=self.probs.dtype)[draws], axis=0)
        return _wrap(onehot)

    def log_prob(self, value):
        v = _arr(value)
        logits = jnp.log(jnp.clip(self.probs, 1e-12))
        return _wrap(jsp.gammaln(jnp.sum(v, -1) + 1)
                     - jnp.sum(jsp.gammaln(v + 1), -1)
                     + jnp.sum(v * logits, -1))

    def entropy(self):
        # No closed form for n > 1: Monte-Carlo estimate of -E[log p(X)]
        # (exact for n == 1, where it reduces to the categorical entropy).
        n = self.total_count
        p = jnp.clip(self.probs, 1e-12)
        if n == 1:
            return _wrap(-jnp.sum(p * jnp.log(p), axis=-1))
        samples = self.sample((512,))._data
        return _wrap(-jnp.mean(self.log_prob(_wrap(samples))._data, axis=0))


class Poisson(ExponentialFamily):
    """Poisson(rate). Reference: python/paddle/distribution/poisson.py."""

    def __init__(self, rate, name=None):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape, ())

    @property
    def mean(self):
        return _wrap(self.rate)

    @property
    def variance(self):
        return _wrap(self.rate)

    def sample(self, shape=()):
        out = jrandom.poisson(self._key(), self.rate, self._extend_shape(shape))
        return _wrap(out.astype(self.rate.dtype))

    def log_prob(self, value):
        v = _arr(value)
        return _wrap(v * jnp.log(self.rate) - self.rate - jsp.gammaln(v + 1))

    def entropy(self):
        # series over a truncated support: 30 sigma past the rate covers the
        # mass at any scale (sigma = sqrt(rate))
        r = float(jnp.max(self.rate))
        n = int(r + 30 * math.sqrt(max(r, 1.0)))
        ks = jnp.arange(n + 1, dtype=self.rate.dtype)
        ks = ks.reshape((n + 1,) + (1,) * len(self.batch_shape))
        lp = ks * jnp.log(self.rate) - self.rate - jsp.gammaln(ks + 1)
        return _wrap(-jnp.sum(jnp.exp(lp) * lp, axis=0))

    def kl_divergence(self, other):
        if isinstance(other, Poisson):
            r, s = self.rate, other.rate
            return _wrap(r * jnp.log(r / s) - r + s)
        return super().kl_divergence(other)

    @property
    def _natural_parameters(self):
        return (jnp.log(self.rate),)

    def _log_normalizer(self, n1):
        return jnp.exp(n1)
