"""KL divergence registry.

Reference: python/paddle/distribution/kl.py (``kl_divergence``,
``register_kl`` with MRO-based dispatch).
"""
from __future__ import annotations

import jax.numpy as jnp

from .distribution import Distribution, ExponentialFamily, _wrap

__all__ = ["kl_divergence", "register_kl"]

_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a pairwise KL implementation."""
    def decorator(f):
        _REGISTRY[(cls_p, cls_q)] = f
        return f
    return decorator


def _dispatch(type_p, type_q):
    matches = [(p, q) for (p, q) in _REGISTRY
               if issubclass(type_p, p) and issubclass(type_q, q)]
    if not matches:
        return None
    # most-derived match wins (lexicographic on MRO distance)
    def key(pq):
        p, q = pq
        return (type_p.__mro__.index(p), type_q.__mro__.index(q))
    return _REGISTRY[min(matches, key=key)]


def kl_divergence(p, q):
    """KL(p || q). Tries: direct method on p, the registry, then the
    exponential-family Bregman fallback."""
    fn = _dispatch(type(p), type(q))
    if fn is not None:
        return fn(p, q)
    if type(p) is type(q):
        own = type(p).kl_divergence
        if own is not Distribution.kl_divergence:
            return own(p, q)
    if (isinstance(p, ExponentialFamily) and type(p) is type(q)):
        return _kl_expfamily_expfamily(p, q)
    raise NotImplementedError(
        f"KL divergence between {type(p).__name__} and {type(q).__name__} "
        "is not implemented; use register_kl to add it.")


def _kl_expfamily_expfamily(p, q):
    """Bregman-divergence KL for same-family exponential distributions
    (reference kl.py:209 ``_kl_expfamily_expfamily``)."""
    import jax

    p_nat = [jnp.asarray(x) for x in p._natural_parameters]
    q_nat = [jnp.asarray(x) for x in q._natural_parameters]

    def log_norm_p(*ps):
        return jnp.sum(p._log_normalizer(*ps))

    lg_p = p._log_normalizer(*p_nat)
    lg_q = q._log_normalizer(*q_nat)
    grads = jax.grad(log_norm_p, argnums=tuple(range(len(p_nat))))(*p_nat)
    kl = lg_q - lg_p
    for pn, qn, g in zip(p_nat, q_nat, grads):
        kl = kl - (qn - pn) * g
    return _wrap(kl)


# -- default pairwise rules (mirror reference registrations) ---------------

def _register_defaults():
    from .continuous import (Normal, Uniform, Beta, Gamma, Exponential,
                             Cauchy, Gumbel, Laplace, LogNormal, StudentT)
    from .discrete import Bernoulli, Categorical, Geometric, Poisson, Binomial
    from .multivariate import Dirichlet, MultivariateNormal

    for cls in (Normal, Cauchy, Laplace, Bernoulli, Categorical, Geometric,
                Poisson, Binomial, Dirichlet, MultivariateNormal):
        def make(c):
            def f(p, q):
                return c.kl_divergence(p, q)
            return f
        register_kl(cls, cls)(make(cls))

    @register_kl(LogNormal, LogNormal)
    def _kl_lognormal(p, q):
        return p._base.kl_divergence(q._base)

    @register_kl(Uniform, Uniform)
    def _kl_uniform(p, q):
        r = (q.high - q.low) / (p.high - p.low)
        out = jnp.where((q.low <= p.low) & (p.high <= q.high),
                        jnp.log(r), jnp.inf)
        return _wrap(out)

    @register_kl(Exponential, Exponential)
    def _kl_exponential(p, q):
        ratio = q.rate / p.rate
        return _wrap(jnp.log(1 / ratio) + ratio - 1)

    @register_kl(Gamma, Gamma)
    def _kl_gamma(p, q):
        from jax.scipy import special as jsp
        a1, b1, a2, b2 = p.concentration, p.rate, q.concentration, q.rate
        return _wrap((a1 - a2) * jsp.digamma(a1) - jsp.gammaln(a1)
                     + jsp.gammaln(a2) + a2 * (jnp.log(b1) - jnp.log(b2))
                     + a1 * (b2 - b1) / b1)

    from .continuous import ContinuousBernoulli

    @register_kl(ContinuousBernoulli, ContinuousBernoulli)
    def _kl_cb(p, q):
        # log-density is linear in x, so E_p[log p - log q] needs only p's mean
        eps = 1e-7
        pp = jnp.clip(p.probs, eps, 1 - eps)
        qq = jnp.clip(q.probs, eps, 1 - eps)
        m = p.mean._data
        return _wrap(m * (jnp.log(pp) - jnp.log(qq))
                     + (1 - m) * (jnp.log1p(-pp) - jnp.log1p(-qq))
                     + p._log_norm() - q._log_norm())

    @register_kl(Beta, Beta)
    def _kl_beta(p, q):
        from jax.scipy import special as jsp
        a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
        s1 = a1 + b1
        return _wrap(jsp.betaln(a2, b2) - jsp.betaln(a1, b1)
                     + (a1 - a2) * jsp.digamma(a1) + (b1 - b2) * jsp.digamma(b1)
                     + (a2 - a1 + b2 - b1) * jsp.digamma(s1))


_register_defaults()
