"""Continuous univariate distributions.

Each class mirrors the same-named class in the reference package
(python/paddle/distribution/{normal,uniform,beta,gamma,exponential,cauchy,
chi2,gumbel,laplace,lognormal,student_t,continuous_bernoulli}.py), re-built
on jax.random / jax.scipy.stats.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import random as jrandom
from jax.scipy import special as jsp
from jax.scipy import stats as jstats

from .distribution import Distribution, ExponentialFamily, _arr, _wrap, _shape

__all__ = [
    "Normal", "Uniform", "Beta", "Gamma", "Exponential", "Cauchy", "Chi2",
    "Gumbel", "Laplace", "LogNormal", "StudentT", "ContinuousBernoulli",
]

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


class Normal(ExponentialFamily):
    """Normal(loc, scale). Reference: python/paddle/distribution/normal.py:33."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        batch = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    @property
    def stddev(self):
        return _wrap(jnp.broadcast_to(self.scale, self.batch_shape))

    def rsample(self, shape=()):
        eps = jrandom.normal(self._key(), self._extend_shape(shape), self.loc.dtype)
        return _wrap(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _arr(value)
        var = self.scale ** 2
        return _wrap(-((v - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - _HALF_LOG_2PI)

    def entropy(self):
        out = 0.5 + _HALF_LOG_2PI + jnp.log(jnp.broadcast_to(self.scale, self.batch_shape))
        return _wrap(out)

    def cdf(self, value):
        v = _arr(value)
        return _wrap(0.5 * (1 + jsp.erf((v - self.loc) / (self.scale * math.sqrt(2)))))

    def icdf(self, value):
        v = _arr(value)
        return _wrap(self.loc + self.scale * math.sqrt(2) * jsp.erfinv(2 * v - 1))

    def kl_divergence(self, other):
        if isinstance(other, Normal):
            var_ratio = (self.scale / other.scale) ** 2
            t1 = ((self.loc - other.loc) / other.scale) ** 2
            return _wrap(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
        return super().kl_divergence(other)

    @property
    def _natural_parameters(self):
        s2 = self.scale ** 2
        return (self.loc / s2, -0.5 / s2)

    def _log_normalizer(self, n1, n2):
        return -0.25 * n1 ** 2 / n2 + 0.5 * jnp.log(-math.pi / n2)


class LogNormal(Distribution):
    """exp(Normal(loc, scale)). Reference: python/paddle/distribution/lognormal.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        self._base = Normal(loc, scale)
        super().__init__(self._base.batch_shape, ())

    @property
    def mean(self):
        return _wrap(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return _wrap((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def rsample(self, shape=()):
        return _wrap(jnp.exp(self._base.rsample(shape)._data))

    def sample(self, shape=()):
        return self.rsample(shape)

    def log_prob(self, value):
        v = _arr(value)
        lp = self._base.log_prob(_wrap(jnp.log(v)))._data - jnp.log(v)
        return _wrap(lp)

    def entropy(self):
        return _wrap(self._base.entropy()._data + self.loc)


class Uniform(Distribution):
    """Uniform(low, high). Reference: python/paddle/distribution/uniform.py:30."""

    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)
        batch = jnp.broadcast_shapes(self.low.shape, self.high.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to((self.low + self.high) / 2, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to((self.high - self.low) ** 2 / 12, self.batch_shape))

    def rsample(self, shape=()):
        u = jrandom.uniform(self._key(), self._extend_shape(shape), self.low.dtype)
        return _wrap(self.low + (self.high - self.low) * u)

    def sample(self, shape=()):
        return self.rsample(shape)

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        lp = jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)
        return _wrap(lp)

    def entropy(self):
        return _wrap(jnp.broadcast_to(jnp.log(self.high - self.low), self.batch_shape))

    def cdf(self, value):
        v = _arr(value)
        return _wrap(jnp.clip((v - self.low) / (self.high - self.low), 0.0, 1.0))


class Beta(ExponentialFamily):
    """Beta(alpha, beta). Reference: python/paddle/distribution/beta.py."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        batch = jnp.broadcast_shapes(self.alpha.shape, self.beta.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.alpha / (self.alpha + self.beta), self.batch_shape))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return _wrap(jnp.broadcast_to(self.alpha * self.beta / (s ** 2 * (s + 1)), self.batch_shape))

    def rsample(self, shape=()):
        out = jrandom.beta(self._key(), self.alpha, self.beta, self._extend_shape(shape))
        return _wrap(out)

    def sample(self, shape=()):
        return self.rsample(shape)

    def log_prob(self, value):
        v = _arr(value)
        return _wrap(jstats.beta.logpdf(v, self.alpha, self.beta))

    def entropy(self):
        a, b = self.alpha, self.beta
        ent = (jsp.betaln(a, b) - (a - 1) * jsp.digamma(a) - (b - 1) * jsp.digamma(b)
               + (a + b - 2) * jsp.digamma(a + b))
        return _wrap(jnp.broadcast_to(ent, self.batch_shape))

    @property
    def _natural_parameters(self):
        return (self.alpha - 1, self.beta - 1)

    def _log_normalizer(self, n1, n2):
        return jsp.betaln(n1 + 1, n2 + 1)


class Gamma(ExponentialFamily):
    """Gamma(concentration, rate). Reference: python/paddle/distribution/gamma.py."""

    def __init__(self, concentration, rate, name=None):
        self.concentration = _arr(concentration)
        self.rate = _arr(rate)
        batch = jnp.broadcast_shapes(self.concentration.shape, self.rate.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.concentration / self.rate, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(self.concentration / self.rate ** 2, self.batch_shape))

    def rsample(self, shape=()):
        g = jrandom.gamma(self._key(), self.concentration, self._extend_shape(shape))
        return _wrap(g / self.rate)

    def sample(self, shape=()):
        return self.rsample(shape)

    def log_prob(self, value):
        v = _arr(value)
        a, r = self.concentration, self.rate
        return _wrap(a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v - jsp.gammaln(a))

    def entropy(self):
        a, r = self.concentration, self.rate
        ent = a - jnp.log(r) + jsp.gammaln(a) + (1 - a) * jsp.digamma(a)
        return _wrap(jnp.broadcast_to(ent, self.batch_shape))

    @property
    def _natural_parameters(self):
        return (self.concentration - 1, -self.rate)

    def _log_normalizer(self, n1, n2):
        return jsp.gammaln(n1 + 1) - (n1 + 1) * jnp.log(-n2)


class Chi2(Gamma):
    """Chi2(df) = Gamma(df/2, 1/2). Reference: python/paddle/distribution/chi2.py."""

    def __init__(self, df, name=None):
        df = _arr(df)
        self.df = df
        super().__init__(df / 2, jnp.asarray(0.5, df.dtype))


class Exponential(ExponentialFamily):
    """Exponential(rate). Reference: python/paddle/distribution/exponential.py."""

    def __init__(self, rate, name=None):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape, ())

    @property
    def mean(self):
        return _wrap(1.0 / self.rate)

    @property
    def variance(self):
        return _wrap(1.0 / self.rate ** 2)

    def rsample(self, shape=()):
        e = jrandom.exponential(self._key(), self._extend_shape(shape), self.rate.dtype)
        return _wrap(e / self.rate)

    def sample(self, shape=()):
        return self.rsample(shape)

    def log_prob(self, value):
        v = _arr(value)
        return _wrap(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return _wrap(1.0 - jnp.log(self.rate))

    def cdf(self, value):
        v = _arr(value)
        return _wrap(1 - jnp.exp(-self.rate * v))

    @property
    def _natural_parameters(self):
        return (-self.rate,)

    def _log_normalizer(self, n1):
        return -jnp.log(-n1)


class Cauchy(Distribution):
    """Cauchy(loc, scale). Reference: python/paddle/distribution/cauchy.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        batch = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean.")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance.")

    def rsample(self, shape=()):
        u = jrandom.uniform(self._key(), self._extend_shape(shape), self.loc.dtype)
        return _wrap(self.loc + self.scale * jnp.tan(math.pi * (u - 0.5)))

    def sample(self, shape=()):
        return self.rsample(shape)

    def log_prob(self, value):
        v = _arr(value)
        return _wrap(jstats.cauchy.logpdf(v, self.loc, self.scale))

    def entropy(self):
        out = jnp.log(4 * math.pi * jnp.broadcast_to(self.scale, self.batch_shape))
        return _wrap(out)

    def cdf(self, value):
        v = _arr(value)
        return _wrap(jnp.arctan((v - self.loc) / self.scale) / math.pi + 0.5)

    def kl_divergence(self, other):
        if isinstance(other, Cauchy):
            a = (self.scale + other.scale) ** 2 + (self.loc - other.loc) ** 2
            return _wrap(jnp.log(a / (4 * self.scale * other.scale)))
        return super().kl_divergence(other)


class Gumbel(Distribution):
    """Gumbel(loc, scale). Reference: python/paddle/distribution/gumbel.py."""

    _EULER = 0.57721566490153286060

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        batch = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc + self.scale * self._EULER, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(math.pi ** 2 / 6 * self.scale ** 2, self.batch_shape))

    @property
    def stddev(self):
        return _wrap(jnp.sqrt(self.variance._data))

    def rsample(self, shape=()):
        g = jrandom.gumbel(self._key(), self._extend_shape(shape), self.loc.dtype)
        return _wrap(self.loc + self.scale * g)

    def sample(self, shape=()):
        return self.rsample(shape)

    def log_prob(self, value):
        v = _arr(value)
        z = (v - self.loc) / self.scale
        return _wrap(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        out = jnp.log(jnp.broadcast_to(self.scale, self.batch_shape)) + 1 + self._EULER
        return _wrap(out)

    def cdf(self, value):
        v = _arr(value)
        return _wrap(jnp.exp(-jnp.exp(-(v - self.loc) / self.scale)))


class Laplace(Distribution):
    """Laplace(loc, scale). Reference: python/paddle/distribution/laplace.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        batch = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(2 * self.scale ** 2, self.batch_shape))

    @property
    def stddev(self):
        return _wrap(jnp.broadcast_to(math.sqrt(2.0) * self.scale, self.batch_shape))

    def rsample(self, shape=()):
        l = jrandom.laplace(self._key(), self._extend_shape(shape), self.loc.dtype)
        return _wrap(self.loc + self.scale * l)

    def sample(self, shape=()):
        return self.rsample(shape)

    def log_prob(self, value):
        v = _arr(value)
        return _wrap(-jnp.abs(v - self.loc) / self.scale - jnp.log(2 * self.scale))

    def entropy(self):
        return _wrap(1 + jnp.log(2 * jnp.broadcast_to(self.scale, self.batch_shape)))

    def cdf(self, value):
        v = _arr(value)
        z = (v - self.loc) / self.scale
        return _wrap(0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z)))

    def icdf(self, value):
        v = _arr(value)
        term = v - 0.5
        return _wrap(self.loc - self.scale * jnp.sign(term) * jnp.log1p(-2 * jnp.abs(term)))

    def kl_divergence(self, other):
        if isinstance(other, Laplace):
            # KL(La(m1,b1)||La(m2,b2)) = log(b2/b1) + |m1-m2|/b2 + b1/b2*exp(-|m1-m2|/b1) - 1
            d = jnp.abs(self.loc - other.loc)
            return _wrap(jnp.log(other.scale / self.scale) + d / other.scale
                         + self.scale / other.scale * jnp.exp(-d / self.scale) - 1)
        return super().kl_divergence(other)


class StudentT(Distribution):
    """StudentT(df, loc, scale). Reference: python/paddle/distribution/student_t.py."""

    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _arr(df)
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        batch = jnp.broadcast_shapes(self.df.shape, self.loc.shape, self.scale.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        m = jnp.where(self.df > 1, self.loc, jnp.nan)
        return _wrap(jnp.broadcast_to(m, self.batch_shape))

    @property
    def variance(self):
        v = jnp.where(self.df > 2, self.scale ** 2 * self.df / (self.df - 2),
                      jnp.where(self.df > 1, jnp.inf, jnp.nan))
        return _wrap(jnp.broadcast_to(v, self.batch_shape))

    def rsample(self, shape=()):
        t = jrandom.t(self._key(), self.df, self._extend_shape(shape), self.loc.dtype)
        return _wrap(self.loc + self.scale * t)

    def sample(self, shape=()):
        return self.rsample(shape)

    def log_prob(self, value):
        v = _arr(value)
        z = (v - self.loc) / self.scale
        return _wrap(jstats.t.logpdf(z, self.df) - jnp.log(self.scale))

    def entropy(self):
        d = self.df
        ent = ((d + 1) / 2 * (jsp.digamma((d + 1) / 2) - jsp.digamma(d / 2))
               + jnp.log(jnp.sqrt(d)) + jsp.betaln(d / 2, 0.5) + jnp.log(self.scale))
        return _wrap(jnp.broadcast_to(ent, self.batch_shape))


class ContinuousBernoulli(Distribution):
    """ContinuousBernoulli(probs).

    Reference: python/paddle/distribution/continuous_bernoulli.py.
    """

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _arr(probs)
        self._lims = lims
        super().__init__(self.probs.shape, ())

    def _outside_unstable(self):
        return (self.probs < self._lims[0]) | (self.probs > self._lims[1])

    def _stable_probs(self):
        return jnp.where(self._outside_unstable(), self.probs, self._lims[0])

    def _log_norm(self):
        # log C(p); C = 2 atanh(1-2p) / (1-2p) for p != 0.5, else 2
        p = self._stable_probs()
        out = jnp.log(jnp.abs(2 * jnp.arctanh(1 - 2 * p))) - jnp.log(jnp.abs(1 - 2 * p))
        taylor = math.log(2.0) + 4 / 3 * (self.probs - 0.5) ** 2
        return jnp.where(self._outside_unstable(), out, taylor)

    @property
    def mean(self):
        p = self._stable_probs()
        m = p / (2 * p - 1) + 1 / (2 * jnp.arctanh(1 - 2 * p))
        taylor = 0.5 + (self.probs - 0.5) / 3
        return _wrap(jnp.where(self._outside_unstable(), m, taylor))

    @property
    def variance(self):
        p = self._stable_probs()
        v = p * (p - 1) / (1 - 2 * p) ** 2 + 1 / (2 * jnp.arctanh(1 - 2 * p)) ** 2
        taylor = 1 / 12 - (self.probs - 0.5) ** 2 / 15
        return _wrap(jnp.where(self._outside_unstable(), v, taylor))

    def rsample(self, shape=()):
        u = jrandom.uniform(self._key(), self._extend_shape(shape), self.probs.dtype)
        return self.icdf(_wrap(u))

    def sample(self, shape=()):
        return self.rsample(shape)

    def log_prob(self, value):
        v = _arr(value)
        eps = 1e-7
        p = jnp.clip(self.probs, eps, 1 - eps)
        return _wrap(v * jnp.log(p) + (1 - v) * jnp.log1p(-p) + self._log_norm())

    def cdf(self, value):
        v = _arr(value)
        p = self._stable_probs()
        c = (p ** v * (1 - p) ** (1 - v) + p - 1) / (2 * p - 1)
        out = jnp.where(self._outside_unstable(), c, v)
        return _wrap(jnp.clip(out, 0.0, 1.0))

    def icdf(self, value):
        v = _arr(value)
        p = self._stable_probs()
        x = (jnp.log1p(v * (2 * p - 1) / (1 - p)) /
             (jnp.log(p) - jnp.log1p(-p)))
        return _wrap(jnp.where(self._outside_unstable(), x, v))

    def entropy(self):
        # H = -E[log p(x)] = -(mean*log p + (1-mean)*log(1-p) + log C)
        eps = 1e-7
        p = jnp.clip(self.probs, eps, 1 - eps)
        m = self.mean._data
        return _wrap(-(m * jnp.log(p) + (1 - m) * jnp.log1p(-p) + self._log_norm()))
