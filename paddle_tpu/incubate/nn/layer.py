"""Fused transformer layer classes. reference: python/paddle/incubate/nn/
(layer/fused_transformer.py: FusedMultiHeadAttention, FusedFeedForward,
FusedTransformerEncoderLayer; layer/fused_linear.py FusedLinear;
layer/fused_dropout_add.py FusedDropoutAdd; layer/fused_ec_moe.py).

TPU-native: "fused" is a statement about the compiled program, not the
Python structure — XLA fuses the bias/dropout/residual/norm epilogues into
the matmuls; these classes keep the reference's layer API so models port
unchanged.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, execute
from ...nn.layer.layers import Layer, LayerList
from ... import nn
from . import functional as F

__all__ = ["FusedLinear", "FusedMultiHeadAttention", "FusedFeedForward", "FusedMultiTransformer",
           "FusedTransformerEncoderLayer", "FusedDropoutAdd",
           "FusedBiasDropoutResidualLayerNorm", "FusedEcMoe"]


class FusedLinear(Layer):
    """reference: incubate/nn/layer/fused_linear.py FusedLinear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self._transpose = transpose_weight
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = (self.create_parameter((out_features,), attr=bias_attr,
                                           is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        return F.fused_linear(x, self.weight, self.bias,
                              transpose_weight=self._transpose)


class FusedDropoutAdd(Layer):
    """reference: incubate/nn/layer/fused_dropout_add.py."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self._p = p
        self._mode = mode

    def forward(self, x, y):
        from ...nn import functional as NF
        return NF.dropout(x, self._p, training=self.training,
                          mode=self._mode) + y


class FusedBiasDropoutResidualLayerNorm(Layer):
    """reference: incubate/nn/layer/fused_transformer.py
    FusedBiasDropoutResidualLayerNorm."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self._dropout = dropout_rate
        self._epsilon = epsilon
        self.ln_scale = self.create_parameter((embed_dim,), attr=weight_attr,
                                              default_initializer=nn.initializer.Constant(1.0))
        self.ln_bias = self.create_parameter((embed_dim,), attr=bias_attr,
                                             is_bias=True)
        self.linear_bias = self.create_parameter((embed_dim,), is_bias=True)

    def forward(self, x, residual):
        from ...nn import functional as NF
        h = NF.dropout(x + self.linear_bias, self._dropout,
                       training=self.training)
        return NF.layer_norm(h + residual, (int(self.ln_scale.shape[0]),),
                             self.ln_scale, self.ln_bias, self._epsilon)


class FusedMultiHeadAttention(Layer):
    """Attention with pre/post-LN + residual fused in.
    reference: incubate/nn/layer/fused_transformer.py FusedMultiHeadAttention."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self._dropout = dropout_rate
        self._attn_dropout = attn_dropout_rate
        self._pre_ln = normalize_before
        self._epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            (3, num_heads, self.head_dim, embed_dim), attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            (3, num_heads, self.head_dim), attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            (embed_dim, embed_dim), attr=linear_weight_attr)
        self.linear_bias = self.create_parameter((embed_dim,),
                                                 attr=linear_bias_attr,
                                                 is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            (embed_dim,), attr=pre_ln_scale_attr,
            default_initializer=nn.initializer.Constant(1.0))
        self.pre_ln_bias = self.create_parameter((embed_dim,),
                                                 attr=pre_ln_bias_attr,
                                                 is_bias=True)
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=ln_scale_attr,
            default_initializer=nn.initializer.Constant(1.0))
        self.ln_bias = self.create_parameter((embed_dim,), attr=ln_bias_attr,
                                             is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        from ...nn import functional as NF
        from ...framework import random as _random
        x = query
        residual = x
        if self._pre_ln:
            x = NF.layer_norm(x, (self.embed_dim,), self.pre_ln_scale,
                              self.pre_ln_bias, self._epsilon)
        drop_key = (_random.next_key()
                    if self.training and self._attn_dropout > 0 else None)

        def attn(a, qkv_w, qkv_b, lw, lb):
            B, S, D = a.shape
            qkv = jnp.einsum("bsd,tnhd->tbsnh", a, qkv_w) \
                + qkv_b[:, None, None]
            q, k, v = qkv[0], qkv[1], qkv[2]       # [B, S, H, hd]
            s = jnp.einsum("bsnh,btnh->bnst", q, k) / math.sqrt(self.head_dim)
            if attn_mask is not None:
                m = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask
                m = jnp.asarray(m)
                if m.dtype == jnp.bool_:
                    # paddle semantics: True = keep, False = mask out
                    s = jnp.where(m, s, -1e30)
                else:
                    s = s + m
            p = jax.nn.softmax(s, axis=-1)
            if drop_key is not None:
                keep = jax.random.bernoulli(drop_key, 1 - self._attn_dropout,
                                            p.shape)
                p = jnp.where(keep, p / (1 - self._attn_dropout), 0)
            o = jnp.einsum("bnst,btnh->bsnh", p, v).reshape(B, S, D)
            return o @ lw + lb

        out = execute(attn, x, self.qkv_weight, self.qkv_bias,
                      self.linear_weight, self.linear_bias,
                      _name="fused_mha")
        out = NF.dropout(out, self._dropout, training=self.training)
        out = out + residual
        if not self._pre_ln:
            out = NF.layer_norm(out, (self.embed_dim,), self.ln_scale,
                                self.ln_bias, self._epsilon)
        return out


class FusedFeedForward(Layer):
    """reference: incubate/nn/layer/fused_transformer.py FusedFeedForward."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._pre_ln = normalize_before
        self._epsilon = epsilon
        self._dropout = dropout_rate
        self._act_dropout = (act_dropout_rate if act_dropout_rate is not None
                             else dropout_rate)
        self._act = activation
        self.linear1 = nn.Linear(d_model, dim_feedforward,
                                 weight_attr=linear1_weight_attr,
                                 bias_attr=linear1_bias_attr)
        self.linear2 = nn.Linear(dim_feedforward, d_model,
                                 weight_attr=linear2_weight_attr,
                                 bias_attr=linear2_bias_attr)
        # pre-LN mode normalizes the input with ln1; post-LN mode normalizes
        # the residual sum with ln2 — distinct parameter sets, as in the
        # reference fused op
        self.norm1 = nn.LayerNorm(d_model, epsilon=epsilon,
                                  weight_attr=ln1_scale_attr,
                                  bias_attr=ln1_bias_attr)
        self.norm2 = nn.LayerNorm(d_model, epsilon=epsilon,
                                  weight_attr=ln2_scale_attr,
                                  bias_attr=ln2_bias_attr)

    def forward(self, src):
        from ...nn import functional as NF
        residual = src
        x = self.norm1(src) if self._pre_ln else src
        act = getattr(NF, self._act)
        x = NF.dropout(act(self.linear1(x)), self._act_dropout,
                       training=self.training)
        x = NF.dropout(self.linear2(x), self._dropout, training=self.training)
        x = x + residual
        return x if self._pre_ln else self.norm2(x)


class FusedTransformerEncoderLayer(Layer):
    """reference: incubate/nn/layer/fused_transformer.py
    FusedTransformerEncoderLayer."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(attn_dropout_rate if attn_dropout_rate
                               is not None else dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedEcMoe(Layer):
    """Expert-choice MoE layer. reference: incubate/nn/layer/fused_ec_moe.py."""

    def __init__(self, hidden_size, inter_size, num_experts, act_type="gelu",
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.gate = nn.Linear(hidden_size, num_experts)
        self.e1_w = self.create_parameter((num_experts, hidden_size, inter_size))
        self.e1_b = self.create_parameter((num_experts, 1, inter_size),
                                          is_bias=True)
        self.e2_w = self.create_parameter((num_experts, inter_size, hidden_size))
        self.e2_b = self.create_parameter((num_experts, 1, hidden_size),
                                          is_bias=True)
        self._act = act_type

    def forward(self, x, gate_logits=None):
        g = gate_logits if gate_logits is not None else self.gate(x)

        def f(a, gl, w1, b1, w2, b2):
            probs = jax.nn.softmax(gl, axis=-1)              # [B, S, E]
            h = jnp.einsum("bsd,edh->bseh", a, w1) + b1[:, 0]
            h = (jax.nn.gelu(h) if self._act == "gelu"
                 else jax.nn.relu(h))
            o = jnp.einsum("bseh,ehd->bsed", h, w2) + b2[:, 0]
            return jnp.einsum("bsed,bse->bsd", o, probs)
        return execute(f, x, g, self.e1_w, self.e1_b, self.e2_w, self.e2_b,
                       _name="fused_ec_moe")


class FusedMultiTransformer(Layer):
    """Whole-stack fused decoder: N pre-LN transformer layers in one module.
    reference: incubate/nn/layer/fused_transformer.py FusedMultiTransformer
    (the generation-serving stack). TPU-native: the layer loop is plain
    Python over fused per-layer blocks — XLA fuses each block. Incremental
    decode (cache_kvs/time_step) is not implemented and fails loudly."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, ln_scale_attrs=None,
                 qkv_weight_attrs=None, num_layers=-1, nranks=1, ring_id=-1,
                 name=None, **kw):
        super().__init__()
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer supports pre-LN only (the reference "
                "kernel's layout)")
        if num_layers < 0:
            num_layers = len(qkv_weight_attrs) if qkv_weight_attrs else 1
        self.num_layers = num_layers
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.layers = LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=True)
            for _ in range(num_layers)])

    def forward(self, src, attn_mask=None, caches=None, time_step=None,
                **kw):
        if caches is not None or time_step is not None:
            raise NotImplementedError(
                "FusedMultiTransformer: incremental decode (caches/"
                "time_step) is not implemented; use "
                "incubate.nn.functional.masked_multihead_attention for the "
                "decode step")
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=attn_mask)
        return out
