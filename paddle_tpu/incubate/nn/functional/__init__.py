"""Fused transformer functionals — the TPU hot-op layer.

reference: python/paddle/incubate/nn/functional/ — fused_rms_norm.py,
fused_rotary_position_embedding.py, swiglu.py, fused_moe.py,
block_multihead_attention.py, masked_multihead_attention.py,
variable_length_memory_efficient_attention.py, fused_dot_product_attention.py.

TPU-native: "fused" means one XLA fusion (these compositions fuse fully) or
a Pallas kernel where XLA can't (flash attention). APIs keep reference names
so model code ports verbatim.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....framework.core import Tensor, execute
from ....nn import functional as F

__all__ = ["fused_rms_norm", "fused_layer_norm",
           "fused_rotary_position_embedding", "swiglu", "fused_linear",
           "fused_linear_activation", "fused_bias_dropout_residual_layer_norm",
           "fused_dot_product_attention", "fused_multi_head_attention",
           "fused_feedforward", "masked_multihead_attention",
           "variable_length_memory_efficient_attention",
           "block_multihead_attention", "fused_moe"]


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, **kw):
    """reference: incubate/nn/functional/fused_rms_norm.py. One XLA fusion:
    (optional residual-add) → rms-normalize → scale."""
    args = [x]
    if residual is not None:
        args.append(residual)
    if bias is not None:
        args.append(bias)
    if norm_weight is not None:
        args.append(norm_weight)

    def f(a, *rest):
        i = 0
        if residual is not None:
            a = a + rest[i]; i += 1
        if bias is not None:
            a = a + rest[i]; i += 1
        a32 = a.astype(jnp.float32)
        ms = jnp.mean(a32 * a32, axis=-1, keepdims=True)
        out = (a32 * jax.lax.rsqrt(ms + epsilon)).astype(a.dtype)
        if norm_weight is not None:
            out = out * rest[i]
        return out

    out = execute(f, *args, _name="rms_norm")
    if residual is not None:
        return out, (x + residual if bias is None else x + residual + bias)
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None, **kw):
    if residual is not None:
        x = x + residual
    if bias is not None:
        x = x + bias
    out = F.layer_norm(x, x.shape[-1], norm_weight, norm_bias, epsilon)
    if residual is not None:
        return out, x
    return out


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """RoPE. reference: incubate/nn/functional/fused_rotary_position_embedding.py.
    q/k: (batch, seq, heads, head_dim)."""

    def make_sincos(seq, dim, dtype):
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
        t = jnp.arange(seq, dtype=jnp.float32)
        freqs = jnp.outer(t, inv)  # (seq, dim/2)
        if use_neox_rotary_style:
            emb = jnp.concatenate([freqs, freqs], axis=-1)
        else:
            emb = jnp.repeat(freqs, 2, axis=-1)
        return jnp.sin(emb).astype(dtype), jnp.cos(emb).astype(dtype)

    def rotate_half(x):
        if use_neox_rotary_style:
            x1, x2 = jnp.split(x, 2, axis=-1)
            return jnp.concatenate([-x2, x1], axis=-1)
        x1 = x[..., ::2]
        x2 = x[..., 1::2]
        return jnp.stack([-x2, x1], axis=-1).reshape(x.shape)

    def apply_one(x, s, c, pos):
        if pos is not None:
            s = jnp.take(s, pos, axis=0)
            c = jnp.take(c, pos, axis=0)
            s = s[:, :, None, :]
            c = c[:, :, None, :]
        else:
            s = s[None, :, None, :]
            c = c[None, :, None, :]
        return (x * c + rotate_half(x) * s).astype(x.dtype)

    tensors = [t for t in (q, k, v) if t is not None]
    extra = []
    if sin is not None:
        extra = [sin, cos]
    if position_ids is not None:
        extra.append(position_ids)

    def f(*arrs):
        n = len(tensors)
        qa = arrs[0]
        seq, dim = qa.shape[1], qa.shape[-1]
        idx = n
        if sin is not None:
            s_, c_ = arrs[idx], arrs[idx + 1]
            s_ = s_.reshape(s_.shape[-2], s_.shape[-1])
            c_ = c_.reshape(c_.shape[-2], c_.shape[-1])
            idx += 2
        else:
            s_, c_ = make_sincos(seq, dim, qa.dtype)
        pos = arrs[idx] if position_ids is not None else None
        outs = tuple(apply_one(arrs[i], s_, c_, pos) for i in range(n))
        return outs if len(outs) > 1 else outs[0]

    outs = execute(f, *(tensors + extra), _name="fused_rope")
    if not isinstance(outs, tuple):
        outs = (outs,)
    result = []
    it = iter(outs)
    for t in (q, k, v):
        result.append(next(it) if t is not None else None)
    return tuple(result)


def swiglu(x, y=None, name=None):
    """reference: incubate/nn/functional/swiglu.py — silu(x) * y (y defaults
    to the second half of x)."""
    if y is None:
        def f(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(a1) * a2
        return execute(f, x, _name="swiglu")
    return execute(lambda a, b: jax.nn.silu(a) * b, x, y, _name="swiglu")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def f(a, w, *rest):
        if transpose_weight:
            w = w.T
        out = a @ w
        if rest:
            out = out + rest[0]
        return out
    args = [x, weight] + ([bias] if bias is not None else [])
    return execute(f, *args, _name="linear")


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu"):
    def f(a, w, b):
        if trans_x:
            a = a.T
        if trans_y:
            w = w.T
        out = a @ w + b
        if activation == "gelu":
            return jax.nn.gelu(out)
        if activation == "relu":
            return jax.nn.relu(out)
        return out
    return execute(f, x, y, bias, _name="linear")


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True, mode="upscale_in_train",
                                           name=None):
    out = x if bias is None else x + bias
    out = F.dropout(out, dropout_rate, training=training, mode=mode)
    out = out + residual
    return F.layer_norm(out, out.shape[-1], ln_scale, ln_bias, ln_epsilon)


def fused_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                is_causal=False, training=True, **kw):
    return F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                          dropout_p=dropout_p,
                                          is_causal=is_causal, training=training)


def fused_multi_head_attention(*args, **kwargs):
    raise NotImplementedError(
        "use nn.MultiHeadAttention (XLA fuses the projections + attention)")


def fused_feedforward(*args, **kwargs):
    raise NotImplementedError(
        "use Linear+activation composition (one XLA fusion on TPU)")


def masked_multihead_attention(x, cache_kv=None, *args, **kwargs):
    raise NotImplementedError(
        "decode-time MHA: see paddle_tpu.ops.pallas.decode_attention (planned)")


def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False,
                                               pre_cache_length=0):
    # static-shape TPU design: dense attention with a length mask
    import numpy as np
    def f(q, k, v, sl, kl, *rest):
        b, h, sq, d = q.shape  # this API uses (b, h, s, d)
        sk = k.shape[2]
        qv = jnp.swapaxes(q, 1, 2)
        kv_ = jnp.swapaxes(k, 1, 2)
        vv = jnp.swapaxes(v, 1, 2)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qv, kv_,
                            preferred_element_type=jnp.float32)
        s = scale if scale is not None else 1.0 / (d ** 0.5)
        logits = logits * s
        kmask = jnp.arange(sk)[None, :] < kl[:, None]
        logits = jnp.where(kmask[:, None, None, :], logits, -1e30)
        if causal:
            cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
            logits = jnp.where(cm, logits, -1e30)
        if rest:
            logits = logits + rest[0]
        p = jax.nn.softmax(logits, -1).astype(v.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, vv)
        return jnp.swapaxes(out, 1, 2)
    args = [query, key, value, seq_lens, kv_seq_lens] + ([mask] if mask is not None else [])
    return execute(f, *args, _name="varlen_attention")


def block_multihead_attention(qkv, key_cache, value_cache, seq_lens,
                              block_tables, write_pos=None, num_heads=None,
                              num_kv_heads=None, name=None, **kwargs):
    """Paged-KV decode attention. reference:
    incubate/nn/functional/block_multihead_attention.py + CUDA kernel
    phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu.

    Decode-phase subset: qkv [B, (H + 2*KVH) * D] packed single new token;
    caches [num_blocks, block_size, KVH, D]; block_tables [B, max_blocks];
    seq_lens [B] length INCLUDING the new token. Writes the new K/V into the
    cache, attends over the paged prefix. Returns (out [B, H*D], k_cache,
    v_cache). Full serving loop: paddle_tpu.ops.paged_attention.
    """
    from ....ops.paged_attention import (paged_attention_decode,
                                         write_to_cache)
    dropped = {k: v for k, v in kwargs.items() if v is not None}
    if dropped:
        raise NotImplementedError(
            "block_multihead_attention: unsupported reference arguments "
            f"{sorted(dropped)} would change numerics if ignored; apply "
            "rope/bias to qkv before calling (see "
            "fused_rotary_position_embedding)")
    kvh = key_cache.shape[2] if num_kv_heads is None else num_kv_heads
    d = key_cache.shape[3]

    def f(qkv_a, kc, vc, lens, tables):
        B = qkv_a.shape[0]
        h = qkv_a.shape[1] // d - 2 * kvh
        q, k_new, v_new = jnp.split(
            qkv_a.reshape(B, -1, d), [h, h + kvh], axis=1)
        pos = lens - 1 if write_pos is None else write_pos
        kc, vc = write_to_cache(kc, vc, k_new, v_new, tables, pos)
        out = paged_attention_decode(q, kc, vc, tables, lens)
        return out.reshape(B, h * d), kc, vc

    return execute(f, qkv, key_cache, value_cache, seq_lens, block_tables,
                   _name="block_multihead_attention")


def fused_moe(x, gate_weight, expert_weights1, expert_bias1, expert_weights2,
              expert_bias2, quant_method="None", moe_topk=2, norm_topk_prob=True):
    """Dense-einsum MoE (every token × every expert masked by top-k gate) —
    the XLA-friendly formulation for moderate expert counts; the all-to-all
    EP version lives in incubate.distributed.models.moe."""
    def f(a, gw, w1, b1, w2, b2):
        scores = jax.nn.softmax(a @ gw, axis=-1)
        topv, topi = jax.lax.top_k(scores, moe_topk)
        if norm_topk_prob:
            topv = topv / jnp.sum(topv, -1, keepdims=True)
        n_exp = w1.shape[0]
        onehot = jax.nn.one_hot(topi, n_exp, dtype=a.dtype)  # (..., topk, E)
        gates = jnp.einsum("...ke,...k->...e", onehot, topv)
        h = jnp.einsum("...d,edh->...eh", a, w1) + b1
        h = jax.nn.gelu(h)
        out = jnp.einsum("...eh,ehd->...ed", h, w2) + b2
        return jnp.einsum("...ed,...e->...d", out, gates)
    return execute(f, x, gate_weight, expert_weights1, expert_bias1,
                   expert_weights2, expert_bias2, _name="fused_moe")
